"""Device-resident hyperparameter search — vmapped config populations.

The reference (and Spark's CrossValidator generally) fits one cluster
job per (config, fold) candidate: every candidate pays its own dispatch
round, its own data scan, and its own compile. Here a POPULATION of
same-family configs becomes ONE device program:

- **vmapped configs**: the trainers expose population fit paths
  (trees/logistic/mlp ``*_pop_*``) that vmap a member axis over the
  per-family fit body. Static shapes are the population's maxima
  (max_depth, n_bins, n_rounds, iterations, hidden width); a member's
  smaller hyperparameter rides as a traced mask (``bin_gain_mask`` /
  ``level_allow`` / round-activity / step-gating / width zero-padding)
  constructed so the member's arithmetic is IDENTICAL to its standalone
  fit — per-config results are bit-identical to serial fits for
  dt/rf/lr/mlp (gb: accuracy parity, the PR-7 standard), pinned in
  tests/test_tune.py.
- **masked k-fold CV**: fold membership is the index predicate
  ``row % folds == fold`` evaluated into per-member row-weight masks
  over the ONE resident (n, d) design — never a data copy. A sweep of
  16 configs × 3 folds is 48 members of one vmapped program.
- **successive halving on checkpoint rungs**: the family's natural
  segment boundaries (PR 14's fitckpt units — boost rounds, tree
  batches, adam iterations) are the rungs. After each rung every
  candidate's fold scores are taken by one fixed-shape scoring program
  (unbuilt trees/rounds carry zero mass, so every rung reuses the same
  compile), the bottom half of surviving configs is dropped by zeroing
  masks — survivors' arithmetic is untouched — and the population state
  is checkpointed, so a crashed sweep resumes to identical survivors
  and scores.
- **profile-guided population sizing**: per-member HBM footprint is
  modeled analytically and raised to the family's recorded
  ``peak_hbm_bytes`` watermark (utils/resources.py, models/flops.py);
  the largest candidate count that fits ``LO_TPU_TUNE_HBM_BUDGET_MB``
  runs as one wave, extras spill into sequential waves (counted on
  ``/metrics`` as ``lo_tune_hbm_spill_waves_total``).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from learningorchestra_tpu.models import logistic, mlp, trees
from learningorchestra_tpu.models.base import as_design
from learningorchestra_tpu.models.registry import validate_hparams
from learningorchestra_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, MeshRuntime)
from learningorchestra_tpu.utils import tracing
from learningorchestra_tpu.utils.structlog import get_logger

log = get_logger("tune")

#: Families with a population fit path. nb is a closed-form single pass
#: (nothing to halve) and tx's sequence programs are out of scope.
POP_FAMILIES = ("dt", "rf", "gb", "lr", "mlp")

#: Wave stride for the fitckpt progress integer: progress =
#: wave * stride + units_done_in_wave stays monotone as long as no wave
#: exceeds a million units (rounds/iterations) — far beyond any real
#: sweep.
_WAVE_STRIDE = 1_000_000

# -- /metrics counters (the ``tune`` section; jobs._fault pattern) -----------

_counter_lock = threading.Lock()
_counters = {
    "populations_fitted": 0,     # vmapped waves run to completion
    "candidates_evaluated": 0,   # configs that received a final score
    "rungs_completed": 0,        # segment+score rounds across all waves
    "halving_drops": 0,          # configs dropped before their budget
    "hbm_spill_waves": 0,        # extra waves forced by the HBM budget
    "sweeps_resumed": 0,         # sweeps continued from a checkpoint
}


def _bump(key: str, by: int = 1) -> None:
    with _counter_lock:
        _counters[key] += by


def counters_snapshot() -> Dict[str, int]:
    with _counter_lock:
        return dict(_counters)


# -- validation ---------------------------------------------------------------

def validate_population(family: str, configs: Sequence[Dict[str, Any]],
                        num_classes: Optional[int] = None) -> None:
    """Reject sweeps the population programs cannot run bit-faithfully.

    Beyond per-config hparam validation (unknown names / out-of-range
    values → the serving tier's 406), population members must agree on
    the axes that change PRNG key derivation or program structure:
    ``jax.random.split(key, n)`` values depend on ``n``, so rf members
    must share ``n_trees`` (a member with fewer trees would draw
    different bootstrap keys than its standalone fit); lr members must
    resolve to one solver (newton and adam are different programs); gb's
    population path is the binary reference-parity booster."""
    if family not in POP_FAMILIES:
        raise ValueError(
            f"classifier {family!r} has no population tune path; "
            f"choose from {sorted(POP_FAMILIES)}")
    if not configs or not isinstance(configs, (list, tuple)):
        raise ValueError("tune needs a non-empty list of configs")
    for c in configs:
        validate_hparams(family, c)
    if family == "rf":
        if len({int(c.get("n_trees", 20)) for c in configs}) != 1:
            raise ValueError(
                "rf tune populations must share n_trees: the bootstrap "
                "key split depends on the tree count, so mixed forest "
                "sizes cannot be bit-faithful to standalone fits — "
                "sweep n_trees across separate tune calls")
    if family == "lr":
        if len({_resolve_solver(c, num_classes) for c in configs}) != 1:
            raise ValueError(
                "lr tune populations must resolve to one solver "
                "(newton and adam are different device programs); pin "
                "'solver' explicitly or split the sweep")
    if family == "gb" and num_classes is not None and num_classes != 2:
        raise ValueError(
            "gb tune populations support the binary reference-parity "
            "booster only (num_classes == 2)")


def _resolve_solver(config: Dict[str, Any],
                    num_classes: Optional[int]) -> str:
    solver = str(config.get("solver", "auto"))
    if solver != "auto":
        return solver
    if num_classes is None:
        return "auto"
    # The serial fit's auto rule (models/logistic.py): d is unknown at
    # validation time, so auto resolves per sweep in the driver; here we
    # only need config-level agreement, which "auto" for all satisfies.
    return "auto"


# -- population sizing --------------------------------------------------------

def _per_member_bytes(family: str, n: int, d: int,
                      num_classes: int) -> float:
    """Analytic resident-HBM model for ONE population member: the
    member's share of the vmapped working set (bin matrices, row masks,
    margins, activation transients). Deliberately coarse — it is raised
    to the family's recorded whole-fit watermark below, and the budget
    knob exists for operators to clamp it anyway."""
    C = float(max(num_classes, 2))
    nf = float(n)
    masks = 8.0 * nf                       # train + eval f32 row weights
    if family in ("dt", "rf"):
        return masks + nf * d + 4.0 * nf * (C + 3.0)
    if family == "gb":
        return masks + nf * d + 24.0 * nf
    if family == "lr":
        return masks + 4.0 * nf * C
    # mlp: hidden activations (bf16) + logits; width is bounded by the
    # population max but unknown here — assume the serial default.
    return masks + 2.0 * nf * 256.0 + 4.0 * nf * C


def plan_waves(family: str, configs: Sequence[Dict[str, Any]], *, n: int,
               d: int, num_classes: int, folds: int,
               cfg) -> List[List[int]]:
    """Split config indices into sequential population waves.

    Wave width = the largest count whose modeled footprint
    (``_per_member_bytes`` raised to the family's recorded
    ``peak_hbm_bytes`` watermark, × folds members per config) fits
    ``LO_TPU_TUNE_HBM_BUDGET_MB``, capped by
    ``LO_TPU_TUNE_MAX_POPULATION`` members. Budget 0 = one wave."""
    from learningorchestra_tpu.utils import resources

    cap = max(1, int(cfg.tune_max_population) // max(folds, 1))
    budget = float(cfg.tune_hbm_budget_mb) * (1 << 20)
    if budget > 0:
        per = _per_member_bytes(family, n, d, num_classes)
        wm = resources.family_watermarks().get(family, {})
        per = max(per, float(wm.get("peak_hbm_bytes", 0)))
        fit = int(budget // max(per * max(folds, 1), 1.0))
        width = max(1, min(cap, fit))
    else:
        width = cap
    idxs = list(range(len(configs)))
    waves = [idxs[i:i + width] for i in range(0, len(idxs), width)]
    if len(waves) > 1 and budget > 0:
        _bump("hbm_spill_waves", len(waves) - 1)
    return waves


# -- fold masks ---------------------------------------------------------------

def _fold_masks(n: int, padded: int, folds: int
                ) -> Tuple[List[int], np.ndarray, np.ndarray]:
    """(fold_ids, train_masks (F, padded), eval_masks (F, padded)) as
    f32 row weights over the padded global row index. Fold membership is
    ``row % folds == fid``; fid = -1 (folds <= 1) trains AND scores on
    every valid row."""
    idx = np.arange(padded)
    valid = (idx < n).astype(np.float32)
    if folds <= 1:
        return [-1], valid[None, :], valid[None, :]
    fids = list(range(folds))
    ev = np.stack([valid * (idx % folds == f) for f in fids]
                  ).astype(np.float32)
    tr = valid[None, :] - ev
    return fids, tr, ev


def _put_members(mesh, arr: np.ndarray):
    """Place a (members, rows) host array member-replicated /
    row-sharded — the layout every population program's shard_map
    expects for per-member row weights."""
    return jax.device_put(
        np.asarray(arr), NamedSharding(mesh, P(None, DATA_AXIS)))


def runtime_replicate(mesh, x):
    """Fully-replicated device placement for population-axis vectors."""
    return jax.device_put(np.asarray(x), NamedSharding(mesh, P()))


# -- family drivers -----------------------------------------------------------
#
# A driver owns one wave's device state. Interface:
#   total_units()            — the wave's unit budget (max over members)
#   run_segment(k)           — advance every live member k units
#   scores()                 — per-MEMBER eval-fold accuracy, (Pm,) np
#   set_alive(alive_configs) — (n_cfg,) 0/1; zeroes dropped members' masks
#   ckpt_arrays()            — host arrays for fitckpt.save
#   restore(units, arrays)   — rebuild device state mid-wave
#
# Members are (config, fold) pairs flattened config-major: member
# m = ci * folds + fi.


class _ForestDriver:
    """dt / rf: units are vmapped tree batches (the serial checkpointed
    path's boundaries); trees accumulate host-side per batch exactly
    like ``_run_forest_checkpointed``."""

    def __init__(self, family, runtime, X, y, num_classes, configs,
                 fold_ids, tr_masks, ev_masks):
        mesh = runtime.mesh
        self.mesh = mesh
        self.num_classes = num_classes
        self.configs = configs
        self.nf = len(fold_ids)
        d = X.shape[1]
        depths = [int(c.get("max_depth", 5)) for c in configs]
        nbins = [int(c.get("n_bins", 32)) for c in configs]
        self.max_depth = max(depths)
        self.n_bins = max(nbins)
        if family == "dt":
            self.n_trees = 1
            mtries = [1] * len(configs)
        else:
            self.n_trees = int(configs[0].get("n_trees", 20))
            mtries = [int(c.get("mtry") or max(1, int(np.sqrt(d))))
                      for c in configs]
        self.tb, self.nb = trees._forest_batch_shape(self.n_trees)
        self.M = 2 ** (self.max_depth + 1) - 1

        # Per-config edges at the config's own n_bins, padded to the
        # population max with +inf (x > inf is never true, so the padded
        # codes are bit-identical to binning with the shorter list).
        sample = X if isinstance(X, np.ndarray) else X.sample_rows(200_000)
        cfg_edges = []
        for c, nb_c in zip(configs, nbins):
            e = np.full((d, self.n_bins - 1), np.inf, np.float32)
            if nb_c > 1:
                e[:, :nb_c - 1] = trees.quantile_edges(sample, nb_c)
            cfg_edges.append(e)
        # Per-config bin/level masks and keys, expanded config-major to
        # members. NEG forbids thresholds ≥ a member's n_bins - 1 and
        # levels ≥ its max_depth (see trees._build_tree).
        NEG = trees.NEG
        bmask = np.zeros((len(configs), self.n_bins), np.float32)
        lallow = np.zeros((len(configs), self.max_depth), bool)
        keys = []
        for i, (c, nb_c, dep) in enumerate(zip(configs, nbins, depths)):
            bmask[i, max(nb_c - 1, 0):] = NEG
            lallow[i, :dep] = True
            keys.append(np.asarray(jax.random.split(
                jax.random.PRNGKey(int(c.get("seed", 0))),
                self.nb * self.tb)))

        rep = lambda a: np.repeat(np.asarray(a), self.nf, axis=0)
        self.edges_dev = runtime.replicate(rep(np.stack(cfg_edges)))
        self.bin_mask = runtime.replicate(rep(bmask))
        self.level_allow = runtime.replicate(rep(lallow))
        self.mtry_vec = runtime.replicate(
            rep(np.asarray(mtries, np.int32)))
        self.keys = rep(np.stack(keys))          # (Pm, nb*tb, 2) host
        X_dev, self.n = runtime.shard_rows(as_design(X))
        self.B_pop = trees._bin_features_pop(X_dev, self.edges_dev)
        self.y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
        self.w_base = _put_members(mesh, tr_masks)
        self.ew_dev = _put_members(mesh, ev_masks)
        self.alive_dev = runtime.replicate(
            np.ones(len(configs) * self.nf, np.float32))
        self.done_b = 0
        self.host: Dict[str, np.ndarray] = {}
        self._names = ("feat", "thr", "internal", "leaf")

    def total_units(self) -> int:
        return self.nb

    def run_segment(self, k: int) -> None:
        w_pop = self.w_base * self.alive_dev[:, None]
        for b in range(self.done_b, self.done_b + k):
            outs = trees._fit_forest_pop_batch(
                self.B_pop, self.y_dev, w_pop, self.bin_mask,
                self.level_allow, self.mtry_vec,
                runtime_replicate(
                    self.mesh,
                    self.keys[:, b * self.tb:(b + 1) * self.tb]),
                num_classes=self.num_classes, max_depth=self.max_depth,
                n_bins=self.n_bins, n_trees=self.n_trees, mesh=self.mesh)
            seg = {kk: np.asarray(a)
                   for kk, a in zip(self._names, outs)}
            self.host = ({kk: np.concatenate([self.host[kk], seg[kk]],
                                             axis=1)
                          for kk in self._names} if self.host else seg)
        self.done_b += k

    def _padded_trees(self):
        Pm = len(self.configs) * self.nf
        full = {
            "feat": np.zeros((Pm, self.n_trees, self.M), np.int32),
            "thr": np.zeros((Pm, self.n_trees, self.M), np.int32),
            "internal": np.zeros((Pm, self.n_trees, self.M), bool),
            "leaf": np.zeros((Pm, self.n_trees, self.M,
                              self.num_classes), np.float32),
        }
        if self.host:
            built = min(self.host["feat"].shape[1], self.n_trees)
            for kk in self._names:
                full[kk][:, :built] = self.host[kk][:, :built]
        return full

    def scores(self) -> np.ndarray:
        full = self._padded_trees()
        return np.asarray(trees._forest_pop_scores(
            self.B_pop, self.y_dev, self.ew_dev,
            jnp.asarray(full["feat"]), jnp.asarray(full["thr"]),
            jnp.asarray(full["internal"]), jnp.asarray(full["leaf"]),
            max_depth=self.max_depth, mesh=self.mesh))

    def set_alive(self, alive_configs: np.ndarray) -> None:
        self.alive_dev = runtime_replicate(
            self.mesh, np.repeat(alive_configs.astype(np.float32),
                                 self.nf))

    def ckpt_arrays(self) -> Dict[str, np.ndarray]:
        return dict(self.host)

    def restore(self, units: int, arrays: Dict[str, np.ndarray]) -> None:
        self.host = {kk: arrays[kk] for kk in self._names}
        self.done_b = units


class _GbDriver:
    """gb: units are boost rounds; the margin carries on device between
    segments and is REPLAYED from the stored (activity-scaled) leaf
    values on resume, like the serial checkpointed path."""

    def __init__(self, runtime, X, y, num_classes, configs, fold_ids,
                 tr_masks, ev_masks):
        mesh = runtime.mesh
        self.mesh = mesh
        self.configs = configs
        self.nf = len(fold_ids)
        d = X.shape[1]
        depths = [int(c.get("max_depth", 5)) for c in configs]
        nbins = [int(c.get("n_bins", 32)) for c in configs]
        rounds = [int(c.get("n_rounds", 20)) for c in configs]
        self.max_depth = max(depths)
        self.n_bins = max(nbins)
        self.r_max = max(rounds)
        self.M = 2 ** (self.max_depth + 1) - 1

        sample = X if isinstance(X, np.ndarray) else X.sample_rows(200_000)
        cfg_edges = []
        for c, nb_c in zip(configs, nbins):
            e = np.full((d, self.n_bins - 1), np.inf, np.float32)
            if nb_c > 1:
                e[:, :nb_c - 1] = trees.quantile_edges(sample, nb_c)
            cfg_edges.append(e)
        NEG = trees.NEG
        bmask = np.zeros((len(configs), self.n_bins), np.float32)
        lallow = np.zeros((len(configs), self.max_depth), bool)
        for i, (nb_c, dep) in enumerate(zip(nbins, depths)):
            bmask[i, max(nb_c - 1, 0):] = NEG
            lallow[i, :dep] = True

        rep = lambda a: np.repeat(np.asarray(a), self.nf, axis=0)
        self.edges_dev = runtime.replicate(rep(np.stack(cfg_edges)))
        self.bin_mask = runtime.replicate(rep(bmask))
        self.level_allow = runtime.replicate(rep(lallow))
        self.step_sizes = runtime.replicate(rep(np.asarray(
            [float(c.get("step_size", 0.1)) for c in configs],
            np.float32)))
        self.rounds_m = rep(np.asarray(rounds, np.int32))
        X_dev, self.n = runtime.shard_rows(as_design(X))
        self.B_pop = trees._bin_features_pop(X_dev, self.edges_dev)
        self.y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
        self.w_base = _put_members(mesh, tr_masks)
        self.ew_dev = _put_members(mesh, ev_masks)
        Pm, padded = tr_masks.shape
        self.margin = _put_members(mesh, np.zeros((Pm, padded),
                                                  np.float32))
        self.alive = np.ones(len(configs) * self.nf, np.float32)
        self.done = 0
        self.host: Dict[str, np.ndarray] = {}
        self._names = ("feat", "thr", "internal", "leaf_val")

    def total_units(self) -> int:
        return self.r_max

    def run_segment(self, k: int) -> None:
        ractive = (((self.done + np.arange(k))[None, :]
                    < self.rounds_m[:, None])
                   & (self.alive[:, None] > 0)).astype(np.float32)
        w_pop = self.w_base * jnp.asarray(self.alive)[:, None]
        outs, self.margin = trees._fit_gbt_pop_seg(
            self.B_pop, self.y_dev, w_pop, self.margin, self.step_sizes,
            runtime_replicate(self.mesh, ractive), self.bin_mask,
            self.level_allow, max_depth=self.max_depth,
            n_bins=self.n_bins, n_rounds=k, mesh=self.mesh)
        seg = {kk: np.asarray(a) for kk, a in zip(self._names, outs)}
        self.host = ({kk: np.concatenate([self.host[kk], seg[kk]],
                                         axis=1)
                      for kk in self._names} if self.host else seg)
        self.done += k

    def _padded_trees(self):
        Pm = self.w_base.shape[0]
        full = {
            "feat": np.zeros((Pm, self.r_max, self.M), np.int32),
            "thr": np.zeros((Pm, self.r_max, self.M), np.int32),
            "internal": np.zeros((Pm, self.r_max, self.M), bool),
            "leaf_val": np.zeros((Pm, self.r_max, self.M), np.float32),
        }
        if self.host:
            built = min(self.host["feat"].shape[1], self.r_max)
            for kk in self._names:
                full[kk][:, :built] = self.host[kk][:, :built]
        return full

    def scores(self) -> np.ndarray:
        full = self._padded_trees()
        return np.asarray(trees._gbt_pop_scores(
            self.B_pop, self.y_dev, self.ew_dev,
            jnp.asarray(full["feat"]), jnp.asarray(full["thr"]),
            jnp.asarray(full["internal"]),
            jnp.asarray(full["leaf_val"]), self.step_sizes,
            max_depth=self.max_depth, mesh=self.mesh))

    def set_alive(self, alive_configs: np.ndarray) -> None:
        self.alive = np.repeat(alive_configs.astype(np.float32), self.nf)

    def ckpt_arrays(self) -> Dict[str, np.ndarray]:
        return dict(self.host)

    def restore(self, units: int, arrays: Dict[str, np.ndarray]) -> None:
        self.host = {kk: arrays[kk] for kk in self._names}
        self.done = units
        self.margin = trees._gbt_pop_replay_margin(
            self.B_pop, jnp.asarray(self.host["feat"]),
            jnp.asarray(self.host["thr"]),
            jnp.asarray(self.host["internal"]),
            jnp.asarray(self.host["leaf_val"]), self.step_sizes,
            max_depth=self.max_depth, mesh=self.mesh)


class _LrDriver:
    """lr: units are solver iterations (newton capped at 20 like the
    serial auto rule); per-member lr/l2 ride as traced scalars."""

    def __init__(self, runtime, X, y, num_classes, configs, fold_ids,
                 tr_masks, ev_masks):
        mesh = runtime.mesh
        self.mesh = mesh
        self.num_classes = num_classes
        self.configs = configs
        self.nf = len(fold_ids)
        self.d = X.shape[1]
        solvers = set()
        for c in configs:
            s = str(c.get("solver", "auto"))
            if s == "auto":
                s = ("newton" if num_classes * (self.d + 1)
                     <= logistic._NEWTON_MAX_CD else "adam")
            solvers.add(s)
        if len(solvers) != 1:
            raise ValueError(
                "lr tune populations must resolve to one solver; got "
                f"{sorted(solvers)}")
        self.solver = solvers.pop()
        iters = [int(c.get("iters", 300)) for c in configs]
        if self.solver == "newton":
            iters = [min(i, 20) for i in iters]
        self.it_max = max(iters)

        rep = lambda a: np.repeat(np.asarray(a), self.nf, axis=0)
        self.iters_vec = runtime.replicate(rep(np.asarray(iters,
                                                          np.int32)))
        self.lrs = runtime.replicate(rep(np.asarray(
            [float(c.get("lr", 0.1)) for c in configs], np.float32)))
        self.l2s = runtime.replicate(rep(np.asarray(
            [float(c.get("l2", 1e-4)) for c in configs], np.float32)))
        self.X_dev, self.n = runtime.shard_rows(as_design(X))
        self.y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
        self.mu, self.sigma = logistic._device_stats(
            self.X_dev, runtime.replicate(np.int32(self.n)), mesh=mesh)
        self.masks = _put_members(mesh, tr_masks)
        self.ew_dev = _put_members(mesh, ev_masks)
        self.alive = runtime.replicate(
            np.ones(len(configs) * self.nf, np.float32))
        self.done = 0
        if self.solver == "adam":
            seeds = rep(np.asarray(
                [int(c.get("seed", 0)) for c in configs], np.int32))
            self.params, self.opt_state = logistic._pop_lr_init(
                jnp.asarray(seeds), self.mu, self.sigma, d=self.d,
                num_classes=num_classes)
        else:
            Pm = len(configs) * self.nf
            self.Wz = runtime.replicate(np.zeros(
                (Pm, self.d + 1, num_classes), np.float32))

    def total_units(self) -> int:
        return self.it_max

    def run_segment(self, k: int) -> None:
        t0 = np.int32(self.done)
        if self.solver == "adam":
            self.params, self.opt_state, _ = logistic._fit_pop_adam(
                self.params, self.opt_state, self.X_dev, self.y_dev,
                self.masks, self.mu, self.sigma, self.lrs, self.l2s,
                self.iters_vec, self.alive, t0, iters=k)
        else:
            self.Wz = logistic._fit_pop_newton(
                self.X_dev, self.y_dev, self.masks, self.mu, self.sigma,
                self.l2s, self.iters_vec, self.alive, self.Wz, t0,
                num_classes=self.num_classes, iters=k, mesh=self.mesh)
        self.done += k

    def _Wb(self):
        if self.solver == "adam":
            return self.params["W"], self.params["b"]
        return self.Wz[:, :self.d, :], self.Wz[:, self.d, :]

    def scores(self) -> np.ndarray:
        W, b = self._Wb()
        return np.asarray(logistic._pop_lr_scores(
            W, b, self.mu, self.sigma, self.X_dev, self.y_dev,
            self.ew_dev, mesh=self.mesh))

    def set_alive(self, alive_configs: np.ndarray) -> None:
        self.alive = runtime_replicate(
            self.mesh, np.repeat(alive_configs.astype(np.float32),
                                 self.nf))

    def ckpt_arrays(self) -> Dict[str, np.ndarray]:
        if self.solver == "newton":
            return {"Wz": np.asarray(self.Wz)}
        out = {f"p.{k}": np.asarray(v) for k, v in self.params.items()}
        leaves = jax.tree_util.tree_leaves(self.opt_state)
        out.update({f"o.{i}": np.asarray(v)
                    for i, v in enumerate(leaves)})
        return out

    def restore(self, units: int, arrays: Dict[str, np.ndarray]) -> None:
        self.done = units
        if self.solver == "newton":
            self.Wz = runtime_replicate(self.mesh, arrays["Wz"])
            return
        self.params = {k[2:]: jnp.asarray(v) for k, v in arrays.items()
                       if k.startswith("p.")}
        tdef = jax.tree_util.tree_structure(self.opt_state)
        nleaves = len(jax.tree_util.tree_leaves(self.opt_state))
        self.opt_state = jax.tree_util.tree_unflatten(
            tdef, [jnp.asarray(arrays[f"o.{i}"])
                   for i in range(nleaves)])


class _MlpDriver:
    """mlp: units are adam iterations; member widths are zero-padded to
    the population max after each member initializes at its OWN rounded
    width (the draw depends on the shape)."""

    def __init__(self, runtime, X, y, num_classes, configs, fold_ids,
                 tr_masks, ev_masks):
        mesh = runtime.mesh
        self.mesh = mesh
        self.configs = configs
        self.nf = len(fold_ids)
        d = X.shape[1]
        iters = [int(c.get("iters", 300)) for c in configs]
        self.it_max = max(iters)
        X = as_design(X)
        self.X_dev, self.n = runtime.shard_rows(X)
        if isinstance(X, np.ndarray):
            mu = X.mean(axis=0).astype(np.float32)
            sigma = np.where(X.std(axis=0) < 1e-7, 1.0,
                             X.std(axis=0)).astype(np.float32)
        else:
            mu, sigma = logistic._device_stats(
                self.X_dev, runtime.replicate(np.int32(self.n)),
                mesh=mesh)
            mu, sigma = np.asarray(mu), np.asarray(sigma)
        rep = lambda a: np.repeat(np.asarray(a), self.nf, axis=0)
        self.params, self.opt_state, self.rounded = mlp._pop_mlp_init(
            rep([int(c.get("seed", 0)) for c in configs]),
            rep([int(c.get("hidden", 256)) for c in configs]),
            d, num_classes, mu, sigma,
            model_mult=mesh.shape[MODEL_AXIS])
        self.iters_vec = runtime.replicate(rep(np.asarray(iters,
                                                          np.int32)))
        self.lrs = runtime.replicate(rep(np.asarray(
            [float(c.get("lr", 1e-2)) for c in configs], np.float32)))
        self.l2s = runtime.replicate(rep(np.asarray(
            [float(c.get("l2", 1e-4)) for c in configs], np.float32)))
        self.y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
        self.masks = _put_members(mesh, tr_masks)
        self.ew_dev = _put_members(mesh, ev_masks)
        self.alive = runtime.replicate(
            np.ones(len(configs) * self.nf, np.float32))
        self.done = 0

    def total_units(self) -> int:
        return self.it_max

    def run_segment(self, k: int) -> None:
        self.params, self.opt_state, _ = mlp._run_pop(
            self.params, self.opt_state, self.X_dev, self.y_dev,
            self.masks, self.lrs, self.l2s, self.iters_vec, self.alive,
            np.int32(self.done), iters=k)
        self.done += k

    def scores(self) -> np.ndarray:
        return np.asarray(mlp._pop_mlp_scores(
            self.params, self.X_dev, self.y_dev, self.ew_dev))

    def set_alive(self, alive_configs: np.ndarray) -> None:
        self.alive = runtime_replicate(
            self.mesh, np.repeat(alive_configs.astype(np.float32),
                                 self.nf))

    def ckpt_arrays(self) -> Dict[str, np.ndarray]:
        out = {f"p.{k}": np.asarray(v) for k, v in self.params.items()}
        leaves = jax.tree_util.tree_leaves(self.opt_state)
        out.update({f"o.{i}": np.asarray(v)
                    for i, v in enumerate(leaves)})
        return out

    def restore(self, units: int, arrays: Dict[str, np.ndarray]) -> None:
        self.done = units
        self.params = {k[2:]: jnp.asarray(v) for k, v in arrays.items()
                       if k.startswith("p.")}
        tdef = jax.tree_util.tree_structure(self.opt_state)
        nleaves = len(jax.tree_util.tree_leaves(self.opt_state))
        self.opt_state = jax.tree_util.tree_unflatten(
            tdef, [jnp.asarray(arrays[f"o.{i}"])
                   for i in range(nleaves)])


_DRIVERS = {"dt": _ForestDriver, "rf": _ForestDriver, "gb": _GbDriver,
            "lr": _LrDriver, "mlp": _MlpDriver}


def _make_driver(family, runtime, X, y, num_classes, configs, fold_ids,
                 tr_masks, ev_masks):
    cls = _DRIVERS[family]
    if cls is _ForestDriver:
        return cls(family, runtime, X, y, num_classes, configs,
                   fold_ids, tr_masks, ev_masks)
    return cls(runtime, X, y, num_classes, configs, fold_ids, tr_masks,
               ev_masks)


# -- the sweep ----------------------------------------------------------------

def sweep(runtime: MeshRuntime, X, y, num_classes: int, family: str,
          configs: Sequence[Dict[str, Any]], *, cfg,
          folds: Optional[int] = None, rungs: Optional[int] = None,
          ckpt=None) -> Dict[str, Any]:
    """Run one device-resident sweep; returns the leaderboard document.

    ``ckpt`` is an optional fitckpt context: population state persists
    at every rung boundary, and an interrupted sweep resumes to
    IDENTICAL survivors and scores (the per-family segment arithmetic is
    bit-stable under segmentation, and the alive set / rung history ride
    in the checkpoint meta)."""
    from learningorchestra_tpu import jobs

    validate_population(family, configs, num_classes)
    configs = [dict(c) for c in configs]
    folds = int(cfg.tune_folds if folds is None else folds)
    rungs = int(cfg.tune_rungs if rungs is None else rungs)
    if folds < 1 or folds > 64:
        raise ValueError("tune folds must be in [1, 64]")
    if rungs < 1:
        raise ValueError("tune rungs must be >= 1")
    if jax.process_count() > 1:
        raise ValueError(
            "tune sweeps run single-process: the member-axis mask "
            "placement is not multi-host addressable yet")

    X = as_design(X)
    if not isinstance(X, np.ndarray):
        raise ValueError(
            "tune sweeps need a resident design matrix; materialize the "
            "dataset (streamed designs are fit-only)")
    n = int(len(X))
    padded = n + (-n) % runtime.mesh.shape[DATA_AXIS]
    fold_ids, tr_all, ev_all = _fold_masks(n, padded, folds)
    nf = len(fold_ids)
    d = int(X.shape[1])
    waves = plan_waves(family, configs, n=n, d=d,
                       num_classes=num_classes, folds=nf, cfg=cfg)

    # Resume bookkeeping: the fitckpt meta carries the wave index, the
    # alive set, the rung history and finished waves' results — enough
    # to rebuild the exact orchestration state around the restored
    # device arrays.
    resume = ckpt.load() if ckpt is not None and ckpt.enabled else None
    completed: List[Dict[str, Any]] = []
    resume_wave = -1
    resume_state = None
    if resume is not None:
        progress, arrays, meta = resume
        if meta.get("family") == family and meta.get("waves") == len(
                waves) and meta.get("folds") == folds:
            resume_wave = int(meta.get("wave", 0))
            completed = list(meta.get("completed", []))
            resume_state = (int(progress) % _WAVE_STRIDE, arrays, meta)
            _bump("sweeps_resumed")
            from learningorchestra_tpu.utils import fitckpt

            fitckpt.count_resume()
            jobs.record_job_resume(f"tune_{family}", {
                "wave": resume_wave, "units": resume_state[0]})
        else:
            ckpt.clear()

    results: List[Dict[str, Any]] = list(completed)
    for w, wave_idx in enumerate(waves):
        if w < resume_wave:
            continue          # finished wave — its results rode the meta
        wave_cfgs = [configs[i] for i in wave_idx]
        nc = len(wave_cfgs)
        tr = np.tile(tr_all, (nc, 1))
        ev = np.tile(ev_all, (nc, 1))
        driver = _make_driver(family, runtime, X, y, num_classes,
                              wave_cfgs, fold_ids, tr, ev)
        units = driver.total_units()
        R = max(1, min(rungs, units))
        seg = -(-units // R)
        alive = np.ones(nc, np.float64)
        survived = np.zeros(nc, np.int64)
        fold_scores = np.zeros((nc, nf), np.float64)
        done = 0
        rung_i = 0
        fit_s = 0.0
        if w == resume_wave and resume_state is not None:
            done, arrays, meta = resume_state
            if 0 < done < units:
                driver.restore(done, arrays)
                alive = np.asarray(meta.get("alive",
                                            alive.tolist()), np.float64)
                survived = np.asarray(
                    meta.get("survived", survived.tolist()), np.int64)
                fold_scores = np.asarray(
                    meta.get("fold_scores", fold_scores.tolist()),
                    np.float64)
                rung_i = int(meta.get("rung", 0))
                fit_s = float(meta.get("fit_s", 0.0))
                driver.set_alive(alive)
            else:
                ckpt.clear()
        while done < units:
            k = min(seg, units - done)
            with tracing.span("tune.rung", family=family, wave=w,
                              rung=rung_i, alive=int(alive.sum())):
                t0 = time.monotonic()
                driver.run_segment(k)
                member_scores = driver.scores()
                fit_s += time.monotonic() - t0
            done += k
            rung_i += 1
            _bump("rungs_completed")
            ms = np.asarray(member_scores, np.float64).reshape(nc, nf)
            live = alive > 0
            fold_scores[live] = ms[live]
            survived[live] = rung_i
            if done < units and R > 1 and live.sum() > 1:
                means = fold_scores.mean(axis=1)
                keep = math.ceil(int(live.sum()) / 2)
                # Rank live configs by mean score, ties to the lower
                # index (deterministic across resumes).
                order = sorted(np.flatnonzero(live),
                               key=lambda i: (-means[i], i))
                dropped = order[keep:]
                if dropped:
                    alive[dropped] = 0.0
                    driver.set_alive(alive)
                    _bump("halving_drops", len(dropped))
            jobs.heartbeat()
            if done < units and ckpt is not None and ckpt.enabled:
                ckpt.save(
                    w * _WAVE_STRIDE + done, driver.ckpt_arrays(),
                    meta={"family": family, "wave": w,
                          "waves": len(waves), "folds": folds,
                          "rung": rung_i, "fit_s": fit_s,
                          "alive": alive.tolist(),
                          "survived": survived.tolist(),
                          "fold_scores": fold_scores.tolist(),
                          "completed": results})
        means = fold_scores.mean(axis=1)
        for i, ci in enumerate(wave_idx):
            results.append({
                "config": configs[ci],
                "fold_scores": [round(float(s), 6)
                                for s in fold_scores[i]],
                "mean_score": round(float(means[i]), 6),
                "fit_seconds": round(fit_s, 3),
                "rungs_survived": int(survived[i]),
                "alive": bool(alive[i]),
                "wave": w,
            })
        _bump("populations_fitted")
        _bump("candidates_evaluated", nc)
        # The next wave's resume anchor: this wave is complete, so its
        # results ride the meta and device state restarts fresh.
        if w + 1 < len(waves) and ckpt is not None and ckpt.enabled:
            ckpt.save((w + 1) * _WAVE_STRIDE, {"anchor": np.zeros(1)},
                      meta={"family": family, "wave": w + 1,
                            "waves": len(waves), "folds": folds,
                            "completed": results})
    if ckpt is not None and ckpt.enabled:
        ckpt.clear()

    finishers = [r for r in results if r["alive"]] or results
    winner = max(finishers, key=lambda r: r["mean_score"])
    board = {
        "family": family, "folds": folds, "rungs": rungs,
        "waves": len(waves), "halving": rungs > 1,
        "results": sorted(results, key=lambda r: -r["mean_score"]),
        "winner": winner,
    }
    log.info("tune %s: %d configs x %d folds in %d wave(s); winner "
             "mean_score=%.4f", family, len(configs), folds, len(waves),
             winner["mean_score"])
    return board
