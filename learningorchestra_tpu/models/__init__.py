from learningorchestra_tpu.models.registry import (  # noqa: F401
    CLASSIFIERS, get_trainer)
from learningorchestra_tpu.models.builder import ModelBuilder  # noqa: F401
