"""Model persistence — the capability upgrade SURVEY.md §5 calls for.

The reference *discards* every fitted model: only predictions and metrics
survive (reference model_builder.py:227-248); there is no way to re-use a
classifier on new data. Here every successful fit checkpoints its
parameter pytree with orbax (the TPU-native checkpoint layer: async-safe
array serialization, sharding-aware restore) plus a JSON manifest carrying
everything needed to serve it again: classifier kind, hparams (the static
args of its predictor), the fitted preprocessing state (vocabularies, fill
values, standardization stats), and the training metrics.

``ModelRegistry.load`` rebuilds a ``TrainedModel`` whose predictor comes
from ``registry.predictor_for`` — so a persisted model predicts on any
stored dataset through POST /trained-models/<name>/predictions with the
exact train-time preprocessing applied.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from learningorchestra_tpu.catalog.store import validate_name
from learningorchestra_tpu.config import Settings
from learningorchestra_tpu.models.base import TrainedModel
from learningorchestra_tpu.models.registry import predictor_for


class ModelNotFound(KeyError):
    pass


class ModelRegistry:
    """Disk-backed registry of fitted models under ``store_root/_models``."""

    def __init__(self, cfg: Settings):
        self.cfg = cfg
        # abspath: orbax refuses relative checkpoint paths, and store_root
        # may arrive relative via LO_TPU_STORE_ROOT.
        self.root = os.path.abspath(os.path.join(cfg.store_root, "_models"))
        self._lock = threading.Lock()
        self._recover_interrupted_saves()

    def _recover_interrupted_saves(self) -> None:
        """A crash between save()'s two swap renames leaves the live dir
        missing with the previous version parked at ``.old.<name>`` —
        promote it back, so a durably-saved model never 404s after a
        restart (the crash-recovery discipline the chunk store already
        follows). Leftover ``.tmp.<name>`` staging (crash mid-write, or
        mid-swap once its ``.old.`` source is promoted) is garbage."""
        if not os.path.isdir(self.root):
            return
        for entry in os.listdir(self.root):
            if not entry.startswith(".old."):
                continue
            live = os.path.join(self.root, entry[len(".old."):])
            parked = os.path.join(self.root, entry)
            if os.path.isdir(live):
                shutil.rmtree(parked)       # swap completed; stray aside
            else:
                os.rename(parked, live)
        for entry in os.listdir(self.root):
            if entry.startswith(".tmp."):
                shutil.rmtree(os.path.join(self.root, entry))

    def _dir(self, name: str) -> str:
        validate_name(name)
        return os.path.join(self.root, name)

    # -- write ---------------------------------------------------------------

    def save(self, name: str, model: TrainedModel,
             metrics: Optional[Dict[str, float]] = None,
             preprocess: Optional[Dict[str, Any]] = None) -> None:
        import orbax.checkpoint as ocp

        d = self._dir(name)
        # Replicated params → host numpy before checkpointing: keeps the
        # save a process-local write under multi-process operation (orbax
        # would otherwise coordinate a distributed save that only process 0
        # participates in).
        import jax

        params = jax.tree.map(np.asarray, model.params)
        # Stage the whole new version in a sibling temp dir, then swap by
        # rename: a re-save (hot-swap) must never leave a window where
        # the model is missing — the online tier's version()/load() run
        # concurrently with live /predict traffic, and a transient
        # ModelNotFound maps to a terminal 404 at the client. Leading
        # dot keeps stray dirs (crash mid-save) out of list(), which
        # rejects names not starting with a letter or digit.
        tmp = os.path.join(self.root, f".tmp.{name}")
        old = os.path.join(self.root, f".old.{name}")
        with self._lock:
            for p in (tmp, old):
                if os.path.isdir(p):
                    shutil.rmtree(p)
            os.makedirs(tmp)
            ocp.PyTreeCheckpointer().save(
                os.path.join(tmp, "params"), params)
            manifest = {
                "name": name,
                "kind": model.kind,
                "num_classes": model.num_classes,
                "hparams": model.hparams,
                "metrics": metrics or {},
                "preprocess": preprocess,
                "time_created": time.strftime("%Y-%m-%d %H:%M:%S"),
            }
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f, indent=1)
            # The swap itself: readers hold the same lock, so the brief
            # old→aside / tmp→live two-step is invisible to them.
            man_path = os.path.join(d, "manifest.json")
            prev = None
            if os.path.isdir(d):
                try:
                    pst = os.stat(man_path)
                    prev = (pst.st_mtime_ns, pst.st_size)
                except OSError:
                    pass
                os.rename(d, old)
            os.rename(tmp, d)
            if os.path.isdir(old):
                shutil.rmtree(old)
            # version() tokens on (mtime_ns, size); on filesystems with
            # coarse timestamps a fast re-save can land the same token
            # and the online tier would silently keep serving the OLD
            # params. Enforce strictly-INCREASING mtime across saves
            # (not mere inequality with the previous token — that
            # allows an ABA collision where save3 lands save1's token
            # while the cache still holds save1's params).
            try:
                st = os.stat(man_path)
                if prev is not None and st.st_mtime_ns <= prev[0]:
                    os.utime(man_path,
                             ns=(st.st_atime_ns, prev[0] + 1))
            except OSError:
                pass

    # -- read ----------------------------------------------------------------

    def version(self, name: str) -> Tuple[int, int]:
        """Cheap staleness token for the persisted model: the manifest
        file's (mtime_ns, size). ``save`` rewrites the manifest, so any
        re-fit under the same name changes the token — what the online
        tier's AOT program cache keys on (models/aot.py) to hot-swap a
        re-saved model without a restart. Raises ModelNotFound when the
        model is gone."""
        path = os.path.join(self._dir(name), "manifest.json")
        # Lock-free stat on the hot path (one call per /predict): taking
        # the registry lock here would head-of-line-block every online
        # request behind any in-flight save's orbax write. The stat can
        # only miss an existing model while a save holds the lock
        # mid-swap — so on miss, wait the swap out and re-check before
        # concluding ModelNotFound.
        try:
            st = os.stat(path)
        except OSError:
            with self._lock:
                try:
                    st = os.stat(path)
                except OSError:
                    raise ModelNotFound(name) from None
        return (st.st_mtime_ns, st.st_size)

    def manifest(self, name: str) -> Dict[str, Any]:
        # Same lock-free-read / locked-recheck shape as version():
        # manifests are only ever swapped in whole by rename, so a
        # plain open() sees the old or the new file, never a torn one —
        # only the mid-swap missing-file window needs to wait out the
        # save (taking the lock unconditionally would stall listing and
        # batch predicts behind a seconds-long orbax write).
        try:
            return self._read_manifest(name)
        except ModelNotFound:
            with self._lock:
                return self._read_manifest(name)

    def _read_manifest(self, name: str) -> Dict[str, Any]:
        path = os.path.join(self._dir(name), "manifest.json")
        if not os.path.exists(path):
            raise ModelNotFound(name)
        with open(path) as f:
            return json.load(f)

    def load(self, name: str) -> Tuple[Dict[str, Any], TrainedModel]:
        import jax
        import numpy as np
        import orbax.checkpoint as ocp

        # Whole restore under the lock: a save() swapping the dir while
        # orbax walks the checkpoint files would hand back a torn mix of
        # versions (or crash on vanished files). Loads happen per model
        # (re)load, not per request, so the exclusion is cheap.
        with self._lock:
            man = self._read_manifest(name)
            params = ocp.PyTreeCheckpointer().restore(
                os.path.join(self._dir(name), "params"))
        # Restore to host arrays: orbax would otherwise pin each leaf to
        # the sharding it was saved with, which may mix device placements
        # (and may not exist on the restoring topology at all). Predict
        # jits re-place them wherever the serving mesh lives.
        params = jax.tree.map(np.asarray, params)
        model = TrainedModel(
            kind=man["kind"], params=params,
            predict_proba_fn=predictor_for(man["kind"], man["hparams"]),
            num_classes=man["num_classes"], hparams=man["hparams"])
        return man, model

    def list(self) -> List[Dict[str, Any]]:
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in sorted(os.listdir(self.root)):
            try:
                out.append(self.manifest(name))
            except (ModelNotFound, json.JSONDecodeError, ValueError):
                # Stray entries (temp files, invalid names) are not models.
                continue
        return out

    def exists(self, name: str) -> bool:
        return os.path.exists(os.path.join(self._dir(name), "manifest.json"))

    def delete(self, name: str) -> None:
        d = self._dir(name)
        with self._lock:
            if not os.path.isdir(d):
                raise ModelNotFound(name)
            shutil.rmtree(d)
