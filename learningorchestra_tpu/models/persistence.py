"""Model persistence — the capability upgrade SURVEY.md §5 calls for.

The reference *discards* every fitted model: only predictions and metrics
survive (reference model_builder.py:227-248); there is no way to re-use a
classifier on new data. Here every successful fit checkpoints its
parameter pytree with orbax (the TPU-native checkpoint layer: async-safe
array serialization, sharding-aware restore) plus a JSON manifest carrying
everything needed to serve it again: classifier kind, hparams (the static
args of its predictor), the fitted preprocessing state (vocabularies, fill
values, standardization stats), and the training metrics.

``ModelRegistry.load`` rebuilds a ``TrainedModel`` whose predictor comes
from ``registry.predictor_for`` — so a persisted model predicts on any
stored dataset through POST /trained-models/<name>/predictions with the
exact train-time preprocessing applied.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from learningorchestra_tpu.catalog.store import validate_name
from learningorchestra_tpu.config import Settings
from learningorchestra_tpu.models.base import TrainedModel
from learningorchestra_tpu.models.registry import predictor_for


class ModelNotFound(KeyError):
    pass


class ModelRegistry:
    """Disk-backed registry of fitted models under ``store_root/_models``."""

    def __init__(self, cfg: Settings):
        self.cfg = cfg
        # abspath: orbax refuses relative checkpoint paths, and store_root
        # may arrive relative via LO_TPU_STORE_ROOT.
        self.root = os.path.abspath(os.path.join(cfg.store_root, "_models"))
        self._lock = threading.Lock()

    def _dir(self, name: str) -> str:
        validate_name(name)
        return os.path.join(self.root, name)

    # -- write ---------------------------------------------------------------

    def save(self, name: str, model: TrainedModel,
             metrics: Optional[Dict[str, float]] = None,
             preprocess: Optional[Dict[str, Any]] = None) -> None:
        import orbax.checkpoint as ocp

        d = self._dir(name)
        # Replicated params → host numpy before checkpointing: keeps the
        # save a process-local write under multi-process operation (orbax
        # would otherwise coordinate a distributed save that only process 0
        # participates in).
        import jax

        params = jax.tree.map(np.asarray, model.params)
        with self._lock:
            if os.path.isdir(d):
                shutil.rmtree(d)
            os.makedirs(d)
            ocp.PyTreeCheckpointer().save(
                os.path.join(d, "params"), params)
            manifest = {
                "name": name,
                "kind": model.kind,
                "num_classes": model.num_classes,
                "hparams": model.hparams,
                "metrics": metrics or {},
                "preprocess": preprocess,
                "time_created": time.strftime("%Y-%m-%d %H:%M:%S"),
            }
            with open(os.path.join(d, "manifest.json"), "w") as f:
                json.dump(manifest, f, indent=1)

    # -- read ----------------------------------------------------------------

    def manifest(self, name: str) -> Dict[str, Any]:
        path = os.path.join(self._dir(name), "manifest.json")
        if not os.path.exists(path):
            raise ModelNotFound(name)
        with open(path) as f:
            return json.load(f)

    def load(self, name: str) -> Tuple[Dict[str, Any], TrainedModel]:
        import jax
        import numpy as np
        import orbax.checkpoint as ocp

        man = self.manifest(name)
        params = ocp.PyTreeCheckpointer().restore(
            os.path.join(self._dir(name), "params"))
        # Restore to host arrays: orbax would otherwise pin each leaf to
        # the sharding it was saved with, which may mix device placements
        # (and may not exist on the restoring topology at all). Predict
        # jits re-place them wherever the serving mesh lives.
        params = jax.tree.map(np.asarray, params)
        model = TrainedModel(
            kind=man["kind"], params=params,
            predict_proba_fn=predictor_for(man["kind"], man["hparams"]),
            num_classes=man["num_classes"], hparams=man["hparams"])
        return man, model

    def list(self) -> List[Dict[str, Any]]:
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in sorted(os.listdir(self.root)):
            try:
                out.append(self.manifest(name))
            except (ModelNotFound, json.JSONDecodeError, ValueError):
                # Stray entries (temp files, invalid names) are not models.
                continue
        return out

    def exists(self, name: str) -> bool:
        return os.path.exists(os.path.join(self._dir(name), "manifest.json"))

    def delete(self, name: str) -> None:
        d = self._dir(name)
        with self._lock:
            if not os.path.isdir(d):
                raise ModelNotFound(name)
            shutil.rmtree(d)
