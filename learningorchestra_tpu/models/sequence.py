"""Sequence-classifier trainer ("tx") — the transformer as a product
surface.

Round 3 left the transformer/ring-attention tier (models/transformer.py)
tested and benched but unreachable from the REST API (VERDICT r3 §5: "a
capability without a user"). This adapter registers it in the classifier
registry next to {lr,dt,rf,gb,nb,mlp}: a stored dataset whose feature
columns are token ids trains through POST /models with
``classificators_list: ["tx"]``, persists via orbax, and re-serves
through /trained-models like every other family.

The train step is the full 3-axis SPMD program (data × model × seq):
batch rows shard over ``data``, attention heads / FFN hidden over
``model`` (Megatron-style), and sequence length over ``seq`` with exact
ring attention (parallel/ring_attention.py) — the REST surface is a thin
adapter over exactly the machinery ``dryrun_multichip`` compiles for
pods. No reference behavior exists to match (the reference predates
sequence models, SURVEY.md §5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from learningorchestra_tpu.models.base import TrainedModel
from learningorchestra_tpu.models.transformer import (
    TxConfig, forward_reference, init_params, make_train_step, shard_params)
from learningorchestra_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, SEQ_AXIS, MeshRuntime)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def fit(runtime: MeshRuntime, X: np.ndarray, y: np.ndarray,
        num_classes: int, seed: int = 0, *, d_model: int = 64,
        n_heads: int = 4, n_layers: int = 2, d_ff: int = 128,
        vocab: int = 0, train_steps: int = 300, batch: int = 1024,
        lr: float = 1e-3, causal: bool = False,
        remat: bool = False) -> TrainedModel:
    """Token-column design matrix → fitted transformer classifier.

    The feature columns ARE the sequence: column j holds token id at
    position j (the design matrix arrives float32; values cast back to
    int). ``vocab=0`` infers the vocabulary from the data.
    """
    mesh = runtime.mesh
    tokens_all = np.maximum(np.asarray(X, np.float32), 0.0).astype(np.int32)
    n, T = tokens_all.shape
    if n == 0 or T == 0:
        raise ValueError("tx needs at least one row and one token column")
    if not vocab:
        vocab = int(tokens_all.max()) + 1
    vocab = max(int(vocab), 2)
    tokens_all = np.minimum(tokens_all, vocab - 1)

    # Round every sharded dimension up to its mesh axis: T to the seq
    # axis (pad token 0), heads/FFN to the model axis, batch to the data
    # axis — the same program then runs on one chip or a full dp×tp×sp
    # pod mesh.
    S = mesh.shape[SEQ_AXIS]
    Dax = mesh.shape[DATA_AXIS]
    M = mesh.shape[MODEL_AXIS]
    T_pad = _round_up(T, S)
    if T_pad > T:
        tokens_all = np.pad(tokens_all, ((0, 0), (0, T_pad - T)))
    n_heads = _round_up(max(n_heads, 1), M)
    d_ff = _round_up(max(d_ff, 1), M)
    d_model = _round_up(max(d_model, n_heads), n_heads)
    batch = min(_round_up(batch, Dax), _round_up(n, Dax))

    cfg = TxConfig(vocab=vocab, d_model=d_model, n_heads=n_heads,
                   n_layers=n_layers, d_ff=d_ff, n_classes=num_classes,
                   max_len=T_pad, causal=causal, remat=remat)
    params = shard_params(init_params(jax.random.PRNGKey(seed), cfg),
                          cfg, mesh)
    opt = optax.adam(lr)
    opt_state = opt.init(params)   # zeros_like → inherits shardings
    train_step = make_train_step(cfg, mesh, opt)

    tok_sharding = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
    lab_sharding = NamedSharding(mesh, P(DATA_AXIS))
    y_all = np.asarray(y, np.int32)
    rng = np.random.default_rng(seed)
    # XLA's CPU backend can abort/deadlock when collective programs
    # pipeline deeply (shared thunk pool — see viz/tsne.py's identical
    # mitigation), so the simulated-mesh rig serializes steps; TPU keeps
    # the async dispatch queue.
    sync_steps = jax.default_backend() == "cpu"
    for _ in range(int(train_steps)):
        sel = rng.integers(0, n, batch)
        bt = jax.device_put(tokens_all[sel], tok_sharding)
        bl = jax.device_put(y_all[sel], lab_sharding)
        params, opt_state, _loss = train_step(params, opt_state, bt, bl)
        if sync_steps:
            jax.block_until_ready(_loss)

    # Replicate the fitted params: predict then runs the unsharded
    # forward under plain data parallelism on any topology, and
    # checkpointing stays a process-local numpy write (persistence.py).
    params = jax.device_put(params, NamedSharding(mesh, P()))
    hp = {"vocab": vocab, "d_model": d_model, "n_heads": n_heads,
          "n_layers": n_layers, "d_ff": d_ff, "n_classes": num_classes,
          "max_len": T_pad, "causal": causal, "train_steps": train_steps,
          "lr": lr}
    return TrainedModel(kind="tx", params=params,
                        predict_proba_fn=predictor(hp),
                        num_classes=num_classes, hparams=hp)


def predictor(hparams: dict):
    """(params, X_dev) → probs for a (possibly restored) tx model."""
    cfg = TxConfig(vocab=int(hparams["vocab"]),
                   d_model=int(hparams["d_model"]),
                   n_heads=int(hparams["n_heads"]),
                   n_layers=int(hparams["n_layers"]),
                   d_ff=int(hparams["d_ff"]),
                   n_classes=int(hparams["n_classes"]),
                   max_len=int(hparams["max_len"]),
                   causal=bool(hparams.get("causal", False)))

    @jax.jit
    def proba(params, X):
        tokens = jnp.clip(X.astype(jnp.int32), 0, cfg.vocab - 1)
        pad = cfg.max_len - tokens.shape[1]
        if pad < 0:
            raise ValueError(
                f"dataset has {tokens.shape[1]} token columns but the "
                f"model was trained with max_len {cfg.max_len}")
        if pad:
            tokens = jnp.pad(tokens, ((0, 0), (0, pad)))
        return jax.nn.softmax(
            forward_reference(params, tokens, cfg=cfg), axis=-1)

    return proba
