"""Evaluation metrics, device-side.

The reference evaluates each fitted model with two Spark
``MulticlassClassificationEvaluator`` jobs — metricName "f1" (weighted by
class support) and "accuracy" (reference model_builder.py:206-225). Both are
reproduced here from a single confusion matrix built with one scatter-add
pass on device, so evaluation costs one kernel instead of two cluster jobs.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("num_classes",))
def confusion_matrix(y_true: jax.Array, y_pred: jax.Array,
                     num_classes: int) -> jax.Array:
    idx = y_true * num_classes + y_pred
    flat = jnp.zeros(num_classes * num_classes, jnp.float32).at[idx].add(1.0)
    return flat.reshape(num_classes, num_classes)


def classification_metrics(y_true: np.ndarray, y_pred: np.ndarray,
                           num_classes: int) -> Dict[str, float]:
    """accuracy + support-weighted F1 (pyspark's default "f1")."""
    cm = np.asarray(confusion_matrix(
        jnp.asarray(y_true, jnp.int32), jnp.asarray(y_pred, jnp.int32),
        num_classes))
    support = cm.sum(axis=1)
    tp = np.diag(cm)
    pred_pos = cm.sum(axis=0)
    precision = np.where(pred_pos > 0, tp / np.maximum(pred_pos, 1), 0.0)
    recall = np.where(support > 0, tp / np.maximum(support, 1), 0.0)
    denom = precision + recall
    f1 = np.where(denom > 0, 2 * precision * recall / np.maximum(denom, 1e-12),
                  0.0)
    total = support.sum()
    weighted_f1 = float((f1 * support).sum() / max(total, 1))
    accuracy = float(tp.sum() / max(total, 1))
    return {"f1": weighted_f1, "accuracy": accuracy}
