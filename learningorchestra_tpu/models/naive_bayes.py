"""Naive Bayes trainer ("nb" in the classifier registry).

The reference's "nb" is ``pyspark.ml.classification.NaiveBayes`` — a
single-pass sufficient-statistics fit distributed over executors (reference
model_builder.py:156). TPU-native design: Gaussian naive Bayes as one jitted
pass — per-class masked sums of x and x² over the row-sharded design matrix
(XLA reduces the sharded row axis with an ICI all-reduce), giving class
priors, means, and variances in a single device program. Gaussian rather
than the reference's multinomial event model because stored datasets carry
signed continuous features, which multinomial NB cannot ingest without a
lossy shift; metrics on the reference's own Titanic workload are comparable
(see tests/test_models.py parity suite).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.models.base import TrainedModel
from learningorchestra_tpu.parallel.mesh import MeshRuntime

_VAR_FLOOR = 1e-6


@partial(jax.jit, static_argnames=("num_classes",))
def _fit(X, y, n_valid, *, num_classes, smoothing):
    n, d = X.shape
    mask = (jnp.arange(n) < n_valid).astype(jnp.float32)
    onehot = jax.nn.one_hot(y, num_classes, dtype=jnp.float32) * mask[:, None]
    counts = onehot.sum(axis=0)                      # (C,)
    sums = onehot.T @ X                              # (C, d) — MXU contraction
    sqsums = onehot.T @ (X * X)                      # (C, d)
    denom = jnp.maximum(counts, 1.0)[:, None]
    mean = sums / denom
    var = jnp.maximum(sqsums / denom - mean ** 2, _VAR_FLOOR) + smoothing
    prior = jnp.log(jnp.maximum(counts, 1.0) / jnp.maximum(counts.sum(), 1.0))
    return {"mean": mean, "var": var, "log_prior": prior}


@jax.jit
def _predict_proba(params, X):
    mean, var, log_prior = params["mean"], params["var"], params["log_prior"]
    # log N(x; mu, var) summed over features, per class: (n, C)
    x2 = ((X[:, None, :] - mean[None]) ** 2) / var[None]
    loglik = -0.5 * (x2 + jnp.log(2.0 * jnp.pi * var)[None]).sum(axis=-1)
    return jax.nn.softmax(loglik + log_prior[None], axis=-1)


def fit(runtime: MeshRuntime, X: np.ndarray, y: np.ndarray,
        num_classes: int, seed: int = 0, *,
        smoothing: float = 1e-3) -> TrainedModel:
    X_dev, n = runtime.shard_rows(np.asarray(X, np.float32))
    y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
    params = _fit(X_dev, y_dev, runtime.replicate(np.int32(n)),
                  num_classes=num_classes,
                  smoothing=runtime.replicate(np.float32(smoothing)))
    return TrainedModel(kind="nb", params=params,
                        predict_proba_fn=_predict_proba,
                        num_classes=num_classes,
                        hparams={"smoothing": smoothing})
