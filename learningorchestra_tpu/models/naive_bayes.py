"""Naive Bayes trainer ("nb" in the classifier registry).

The reference's "nb" is ``pyspark.ml.classification.NaiveBayes`` — a
single-pass sufficient-statistics fit distributed over executors (reference
model_builder.py:156). TPU-native design: Gaussian naive Bayes as one jitted
pass — per-class masked sums of x and x² over the row-sharded design matrix
(XLA reduces the sharded row axis with an ICI all-reduce), giving class
priors, means, and variances in a single device program. Gaussian rather
than the reference's multinomial event model because stored datasets carry
signed continuous features, which multinomial NB cannot ingest without a
lossy shift; metrics on the reference's own Titanic workload are comparable
(see tests/test_models.py parity suite).

For strict reference parity, ``event_model="multinomial"`` fits the
reference's exact event model (count-likelihood with Laplace smoothing,
as pyspark's NaiveBayes defaults) — valid only for non-negative features,
which it validates up front.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.models.base import TrainedModel, as_design
from learningorchestra_tpu.parallel.mesh import MeshRuntime

_VAR_FLOOR = 1e-6


def _class_stats(y, n, n_valid, num_classes):
    """Masked per-class machinery shared by both event models:
    (onehot_T, counts, log_prior, mask). One-hot built transposed (C, n)
    — the long row axis sits in lanes; an (n, C<128) layout would
    lane-pad to 128 columns (GBs at 11M rows)."""
    mask = (jnp.arange(n) < n_valid).astype(jnp.float32)
    classes = jnp.arange(num_classes, dtype=y.dtype)[:, None]
    onehot_T = (y[None, :] == classes).astype(jnp.float32) * mask[None, :]
    counts = onehot_T.sum(axis=1)                    # (C,)
    prior = jnp.log(jnp.maximum(counts, 1.0)
                    / jnp.maximum(counts.sum(), 1.0))
    return onehot_T, counts, prior, mask


@partial(jax.jit, static_argnames=("num_classes",))
def _fit(X, y, n_valid, *, num_classes, smoothing):
    n, d = X.shape
    onehot_T, counts, prior, mask = _class_stats(y, n, n_valid, num_classes)
    # Center features by their global mean before the moment matmuls:
    # E[x²]−E[x]² cancels catastrophically in float32 for unstandardized
    # large-magnitude features; on centered data both moments are O(var).
    total = jnp.maximum(mask.sum(), 1.0)
    center = (mask @ X) / total                      # (d,) global feature mean
    Xc = X - center[None, :]
    sums = onehot_T @ Xc                             # (C, d) — MXU contraction
    sqsums = onehot_T @ (Xc * Xc)                    # (C, d)
    denom = jnp.maximum(counts, 1.0)[:, None]
    mean_c = sums / denom
    var = jnp.maximum(sqsums / denom - mean_c ** 2, _VAR_FLOOR) + smoothing
    return {"mean": mean_c + center[None, :], "var": var,
            "log_prior": prior}


@jax.jit
def _predict_proba(params, X):
    mean, var, log_prior = params["mean"], params["var"], params["log_prior"]
    # log N(x; mu, var) summed over features, per class, in expanded
    # quadratic form: Σ_d (x−μ)²/v = x²·(1/v) − 2x·(μ/v) + Σ μ²/v.
    # Two (n,d)@(d,C) matmuls instead of an (n, C, d) broadcast tensor
    # (which would be gigabytes at HIGGS scale before lane padding).
    # Shifting x and μ by the across-class mean is exact (the shift cancels
    # inside (x−μ)²) and keeps x² small enough that the expanded form
    # doesn't catastrophically cancel for large-magnitude raw features.
    c = mean.mean(axis=0)                              # (d,)
    Xc = X - c[None, :]
    mu = mean - c[None, :]
    inv_v = (1.0 / var).T                              # (d, C)
    mu_v = (mu / var).T                                # (d, C)
    const = ((mu ** 2 / var) + jnp.log(2.0 * jnp.pi * var)).sum(axis=1)
    quad = (Xc * Xc) @ inv_v - 2.0 * (Xc @ mu_v)       # (n, C)
    loglik = -0.5 * (quad + const[None, :])
    return jax.nn.softmax(loglik + log_prior[None], axis=-1)


@partial(jax.jit, static_argnames=("num_classes",))
def _fit_multinomial(X, y, n_valid, *, num_classes, alpha):
    """The reference's exact event model: per-class feature-count sums
    with Laplace smoothing (pyspark NaiveBayes' default multinomial,
    reference model_builder.py:156) — one MXU contraction."""
    n, d = X.shape
    onehot_T, counts, _, _ = _class_stats(y, n, n_valid, num_classes)
    # Spark smooths the class prior too: pi_c = log((n_c + lambda) /
    # (n + numLabels*lambda)) — the unsmoothed prior stays gaussian-only.
    prior = (jnp.log(counts + alpha)
             - jnp.log(counts.sum() + alpha * num_classes))
    Ncd = onehot_T @ X                               # (C, d)
    theta = (jnp.log(Ncd + alpha)
             - jnp.log(Ncd.sum(axis=1, keepdims=True) + alpha * d))
    return {"theta": theta, "log_prior": prior}


@jax.jit
def _predict_multinomial(params, X):
    loglik = X @ params["theta"].T + params["log_prior"][None]
    return jax.nn.softmax(loglik, axis=-1)


def fit(runtime: MeshRuntime, X: np.ndarray, y: np.ndarray,
        num_classes: int, seed: int = 0, *,
        smoothing: Optional[float] = None,
        event_model: str = "gaussian") -> TrainedModel:
    # Per-event-model smoothing defaults: the knob means variance floor
    # for gaussian (1e-3) but Laplace alpha for multinomial, where the
    # reference's pyspark default is lambda = 1.0.
    if smoothing is None:
        smoothing = 1.0 if event_model == "multinomial" else 1e-3

    X = as_design(X)
    X_dev, n = runtime.shard_rows(X)
    if event_model == "multinomial" and X.shape[0] and X.shape[1]:
        # Non-negativity check on device (padding rows are zeros, so they
        # can't mask a negative): lazy designs never exist fully on the
        # host, and the device min is one cheap reduction either way.
        if float(np.asarray(jnp.min(X_dev))) < 0.0:
            raise ValueError(
                "multinomial naive Bayes requires non-negative features "
                "(counts); use the default gaussian event model for signed "
                "continuous data")
    y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
    if event_model == "multinomial":
        params = _fit_multinomial(
            X_dev, y_dev, runtime.replicate(np.int32(n)),
            num_classes=num_classes,
            alpha=runtime.replicate(np.float32(max(smoothing, 1e-9))))
        predict = _predict_multinomial
    elif event_model == "gaussian":
        params = _fit(X_dev, y_dev, runtime.replicate(np.int32(n)),
                      num_classes=num_classes,
                      smoothing=runtime.replicate(np.float32(smoothing)))
        predict = _predict_proba
    else:
        raise ValueError(f"unknown nb event_model {event_model!r}")
    return TrainedModel(kind="nb", params=params,
                        predict_proba_fn=predict,
                        num_classes=num_classes,
                        hparams={"smoothing": smoothing,
                                 "event_model": event_model})
