"""Analytic per-family FLOP counts — the numerator of the bench's MFU.

VERDICT r4/r5 weak #1: "58× a single-core sklearn stand-in" never
established the chip is well used — nothing distinguished 40% MFU from
4%. These formulas count the *algorithmically required* floating-point
work of each trainer's device program (the dominant contraction terms,
from the same shapes the modules document), so

    mfu = flops / (device_s * peak_flops)

is a falsifiable utilization figure next to wall-clock. Counts are
analytic rather than XLA cost-model dumps on purpose: they price the
algorithm, not whatever the compiler materialized, so a bloated lowering
shows up as LOW mfu instead of inflating the numerator to hide itself.

Conventions: one multiply-add = 2 flops; one-hot compare/select passes
count 1 flop per element (they occupy the VPU exactly like an add);
terms an order of magnitude below the leading contraction are dropped.
Shapes/blocking mirror models/logistic.py, models/trees.py,
models/naive_bayes.py — the line references below.

Tree families carry TWO cost models since the fused Pallas kernel path
landed (LO_TPU_TREE_KERNEL, models/trees.py):

- The **oracle path** genuinely executes the dense one-hot contraction,
  so its flops price that emulation (the MXU work the device performs).
- The **kernel path** prices the *algorithm* — a binned scatter-add is
  one accumulate per (row, feature, stat) per level — NOT the dense
  contraction the kernel still uses internally to feed the MXU. The
  contraction term is ~NL·n_bins (≈512× at the defaults) the
  algorithmic accumulate — ~97% multiplications by zero — and pricing
  it would inflate the end-to-end kernel-path numerator ~50× (the bin
  compares and gain terms are shared by both paths): congratulating the
  kernel for doing useless work fast is exactly the "bloated lowering
  hides itself" failure mode above.
  Kernel-path tree fits are therefore memory-bound by design and their
  honest utilization figure is ``bw_util`` — modeled HBM bytes
  (``fit_bytes``) over device time against peak HBM bandwidth — with
  mfu reported alongside as the (low) MXU-work fraction.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from learningorchestra_tpu import config

#: Peak dense-matmul FLOP/s of one TPU v5e chip at bf16 (the dtype the
#: dominant contractions here actually use: trees' histogram matmuls and
#: lr's Newton accumulation run bf16 operands with f32 accumulation).
#: Override with LO_TPU_PEAK_FLOPS (config.peak_flops) for other
#: parts/backends.
V5E_PEAK_BF16 = 197e12

PEAK_FLOPS = config.peak_flops() or V5E_PEAK_BF16

#: Peak HBM bandwidth of one TPU v5e chip (819 GB/s) — the denominator
#: of ``bw_util`` for memory-bound programs (kernel-path tree fits).
#: Override with LO_TPU_PEAK_BW (config.peak_bw).
V5E_HBM_BW = 819e9

PEAK_BW = config.peak_bw() or V5E_HBM_BW


def _tree_kernel_default() -> bool:
    """Whether the fit programs route through the Pallas tree kernels —
    mirrors models/trees.py `_use_tree_kernel` (config flags + backend
    probe) without importing jax at module import time."""
    from learningorchestra_tpu.models import trees

    return trees._use_tree_kernel()


def _tree_build_flops(n: float, d: float, n_bins: float, max_depth: float,
                      n_stats: float, kernel: bool = False) -> float:
    """One level-wise histogram tree (models/trees.py _build_tree).

    Oracle path — per level, per row block: the (NL·S, blk) @
    (blk, d·n_bins) histogram contraction (trees.py _hist_level_xla)
    dominates at 2·n·NL·S·d·n_bins; building the bin one-hot costs
    n·d·n_bins compares and the node-masked stats operand n·NL·S.
    Routing (_sel_col/_sel_table one-hot passes) adds ~n·(2d + 3·NL)
    per level. NL is the fixed per-level node width 2^(max_depth-1).
    Leaf stats add one (S, n) @ (n, M) contraction.

    Kernel path — algorithmic cost only (see module docstring): one
    accumulate per (row, feature, stat) per level (2·n·d·S), the
    n·d·n_bins bin compares, ~5·n routing ops per level, the
    ~6·NL·d·n_bins·S gain evaluation, and n·S leaf accumulates.
    """
    NL = 2 ** max(int(max_depth) - 1, 0)
    M = 2 ** (int(max_depth) + 1) - 1
    if kernel:
        per_level = (2.0 * n * d * n_stats            # binned scatter-add
                     + n * d * n_bins                 # bin one-hot
                     + 6.0 * NL * d * n_bins * n_stats  # split gains
                     + 5.0 * n)                       # routing
        return max_depth * per_level + 2.0 * n * n_stats
    per_level = (2.0 * n * NL * n_stats * d * n_bins   # histogram matmul
                 + n * d * n_bins                      # bin one-hot
                 + n * NL * n_stats                    # stats operand
                 + n * (2.0 * d + 3.0 * NL))           # routing selects
    return max_depth * per_level + 2.0 * n * n_stats * M


def _tree_build_bytes(n: float, d: float, n_bins: float, max_depth: float,
                      n_stats: float, kernel: bool = False) -> float:
    """Modeled HBM traffic of one tree build (the roofline numerator for
    the memory-bound kernel path).

    Kernel path — per level the histogram pass streams the uint8 bin
    matrix (n·d), the f32 stats (4·n·S) and the int32 rel/active columns
    (~8·n); the routing pass re-streams the bin matrix and
    reads+writes assignment (~12·n). Accumulator blocks live in VMEM.
    Leaf pass: stats + assignment once.

    Oracle path adds the materialized contraction operands per level:
    the (blk, d·n_bins) bin one-hot and the (blk, NL·S) node-masked
    stats, each written then read (2× each way) at the operand dtype
    (bf16 on TPU — modeled at 2 bytes).
    """
    hist_level = n * (d + 4.0 * n_stats + 8.0)
    route_level = n * (d + 12.0)
    leaf = n * (4.0 * n_stats + 4.0)
    total = max_depth * (hist_level + route_level) + leaf
    if not kernel:
        NL = 2 ** max(int(max_depth) - 1, 0)
        onehot = 2.0 * 2.0 * n * (d * n_bins + NL * n_stats)
        total += max_depth * onehot + 2.0 * 2.0 * n * (
            2 ** (int(max_depth) + 1) - 1)
    return total


def _binning_flops(n: float, d: float, n_bins: float) -> float:
    """bin_features: fused (n, d, n_bins-1) compare+sum (trees.py:139)."""
    return n * d * (n_bins - 1)


def _descend_flops(n: float, d: float, max_depth: float) -> float:
    """Blocked leaf routing: per depth step, _sel_table×3 (M-wide) +
    _sel_col (d-wide) one-hot passes (trees.py:329-351)."""
    M = 2 ** (int(max_depth) + 1) - 1
    return max_depth * n * (d + 3.0 * M)


def fit_flops(kind: str, n: int, d: int, num_classes: int,
              hparams: Optional[Dict[str, Any]] = None,
              tree_kernel: Optional[bool] = None) -> float:
    """Analytic FLOPs of one family's *fit* device program on (n, d)
    rows. ``hparams`` are the request's overrides; defaults mirror the
    trainer signatures (Spark-2.4 parity defaults). ``tree_kernel``
    selects the tree families' cost model (module docstring); None
    reads the active configuration."""
    hp = dict(hparams or {})
    if kind in ("dt", "rf", "gb") and tree_kernel is None:
        tree_kernel = _tree_kernel_default()
    n, d, C = float(n), float(d), float(max(num_classes, 2))
    if kind == "lr":
        solver = hp.get("solver", "auto")
        d1 = d + 1
        if solver == "auto":
            solver = "newton" if C * d1 <= 256 else "adam"
        if solver == "newton":
            # Per Newton step (logistic.py:138-168): logits 2·n·d1·C, the
            # A-operand n·C·d1, T2 = AᵀA at 2·n·(C·d1)², T1's C blocked
            # d1×d1 contractions at 2·n·C·d1², gradient 2·n·d1·C; plus
            # the (C·d1)³ solve (replicated, negligible at n≫d).
            iters = min(float(hp.get("iters", 300)), 20.0)
            per = (2.0 * n * (C * d1) ** 2 + 2.0 * n * C * d1 ** 2
                   + 5.0 * n * C * d1)
            stats = 4.0 * n * d            # _device_stats two-pass
            return iters * per + stats
        iters = float(hp.get("iters", 300))
        # Adam full-batch value_and_grad ≈ 3× the forward 2·n·d·C matmul.
        return iters * 6.0 * n * d * C + 4.0 * n * d
    if kind == "nb":
        # One pass (naive_bayes.py:50-65): center matmul 2·n·d, the two
        # (C, n) @ (n, d) moment contractions 4·n·C·d, one-hot n·C.
        return 4.0 * n * C * d + 3.0 * n * d + n * C
    if kind in ("dt", "rf"):
        n_trees = float(hp.get("n_trees", 1 if kind == "dt" else 20))
        max_depth = float(hp.get("max_depth", 5))
        n_bins = float(hp.get("n_bins", 32))
        return (_binning_flops(n, d, n_bins)
                + n_trees * _tree_build_flops(n, d, n_bins, max_depth,
                                              n_stats=C,
                                              kernel=bool(tree_kernel)))
    if kind == "gb":
        n_rounds = float(hp.get("n_rounds", 20))
        max_depth = float(hp.get("max_depth", 5))
        n_bins = float(hp.get("n_bins", 32))
        boosters = C if C > 2 else 1.0     # one-vs-rest above binary
        # Per round: grad/hess stats ~6·n, one tree build (S=2 stats),
        # leaf-value descent + margin update (~_descend + n·M select).
        M = 2 ** (int(max_depth) + 1) - 1
        per_round = (_tree_build_flops(n, d, n_bins, max_depth,
                                       n_stats=2.0,
                                       kernel=bool(tree_kernel))
                     + _descend_flops(n, d, max_depth) + n * M + 6.0 * n)
        return boosters * (n_rounds * per_round) + _binning_flops(n, d,
                                                                  n_bins)
    if kind == "mlp":
        hidden = float(hp.get("hidden", 64))
        iters = float(hp.get("iters", 200))
        return iters * 6.0 * n * hidden * (d + C)
    return 0.0


def predict_flops(kind: str, n: int, d: int, num_classes: int,
                  hparams: Optional[Dict[str, Any]] = None) -> float:
    """Analytic FLOPs of one family's probability pass on (n, d) rows."""
    hp = dict(hparams or {})
    n, d, C = float(n), float(d), float(max(num_classes, 2))
    if kind == "lr":
        return 2.0 * n * d * C + 3.0 * n * d
    if kind == "nb":
        # Two (n, d) @ (d, C) matmuls (naive_bayes.py:84).
        return 4.0 * n * d * C + 3.0 * n * d
    if kind in ("dt", "rf", "gb"):
        n_bins = float(hp.get("n_bins", 32))
        max_depth = float(hp.get("max_depth", 5))
        if kind == "gb":
            trees = float(hp.get("n_rounds", 20)) * (C if C > 2 else 1.0)
            leaf_cols = 1.0
        else:
            trees = float(hp.get("n_trees", 1 if kind == "dt" else 20))
            leaf_cols = C
        M = 2 ** (int(max_depth) + 1) - 1
        return (_binning_flops(n, d, n_bins)
                + trees * (_descend_flops(n, d, max_depth)
                           + 2.0 * n * M * leaf_cols))
    if kind == "mlp":
        hidden = float(hp.get("hidden", 64))
        return 2.0 * n * hidden * (d + C)
    return 0.0


def build_flops(kind: str, n_train: int, n_test: int, d: int,
                num_classes: int,
                hparams: Optional[Dict[str, Any]] = None,
                tree_kernel: Optional[bool] = None) -> float:
    """Fit + probability pass — the device program one family contributes
    to a model build (models/builder.py fit device phase)."""
    return (fit_flops(kind, n_train, d, num_classes, hparams,
                      tree_kernel=tree_kernel)
            + predict_flops(kind, n_test, d, num_classes, hparams))


def mfu(flops: float, device_s: float,
        peak_flops: float = 0.0) -> Optional[float]:
    """Achieved fraction of peak: flops / (device_s · peak). None when
    the span is degenerate (failed fit, unmeasured)."""
    peak = peak_flops or PEAK_FLOPS
    if device_s <= 0.0 or peak <= 0.0 or flops <= 0.0:
        return None
    return flops / (device_s * peak)


def fit_bytes(kind: str, n: int, d: int, num_classes: int,
              hparams: Optional[Dict[str, Any]] = None,
              tree_kernel: Optional[bool] = None) -> Optional[float]:
    """Modeled HBM bytes moved by one family's fit device program — the
    roofline numerator for memory-bound programs. Currently modeled for
    the tree families only (the ones the Pallas kernel path turned
    memory-bound); None elsewhere."""
    if kind not in ("dt", "rf", "gb"):
        return None
    hp = dict(hparams or {})
    if tree_kernel is None:
        tree_kernel = _tree_kernel_default()
    n, d, C = float(n), float(d), float(max(num_classes, 2))
    max_depth = float(hp.get("max_depth", 5))
    n_bins = float(hp.get("n_bins", 32))
    binning = 5.0 * n * d                      # read f32, write uint8
    if kind in ("dt", "rf"):
        n_trees = float(hp.get("n_trees", 1 if kind == "dt" else 20))
        return binning + n_trees * _tree_build_bytes(
            n, d, n_bins, max_depth, n_stats=C, kernel=bool(tree_kernel))
    n_rounds = float(hp.get("n_rounds", 20))
    boosters = C if C > 2 else 1.0
    # Per round: the tree build, full-tree descent (bin matrix + assign),
    # and the margin/grad/hess elementwise passes (~5 f32 row vectors).
    per_round = (_tree_build_bytes(n, d, n_bins, max_depth, n_stats=2.0,
                                   kernel=bool(tree_kernel))
                 + n * (d + 4.0) + 20.0 * n)
    return binning + boosters * n_rounds * per_round


def bw_util(bytes_moved: Optional[float], device_s: float,
            peak_bw: float = 0.0) -> Optional[float]:
    """Achieved fraction of peak HBM bandwidth: bytes / (device_s ·
    peak). The utilization figure that matters for memory-bound programs
    (kernel-path tree fits); None when unmodeled or degenerate."""
    peak = peak_bw or PEAK_BW
    if bytes_moved is None or device_s <= 0.0 or peak <= 0.0 \
            or bytes_moved <= 0.0:
        return None
    return bytes_moved / (device_s * peak)
