"""Logistic regression trainer ("lr" in the classifier registry).

The reference's "lr" is ``pyspark.ml.classification.LogisticRegression``
fitted as a distributed iterative Spark job (reference model_builder.py:152,
200). TPU-native design: multinomial logistic regression as one jit-compiled
program. Two solvers:

- **Newton/IRLS** (default whenever ``C·(d+1)`` is small enough for the
  Hessian solve): ~20 second-order steps instead of hundreds of
  first-order ones. Each step is a ``lax.scan`` over row blocks that
  accumulates the gradient and the exact multinomial Hessian with MXU
  contractions — blocking matters because any (n, C<128)-shaped
  intermediate lane-pads to 128 on TPU, so full-batch softmax/residual
  tensors would each cost gigabytes of HBM traffic at 11M rows.
- **Adam scan** (wide-model fallback): full-batch first-order steps on the
  bf16 design matrix.

Rows are sharded across the mesh data axis; losses/moments are masked
means, so their contractions over the sharded row dimension make XLA
insert the ICI all-reduce automatically (no hand-written collectives).
bfloat16 matmuls feed the MXU; parameters stay float32.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from learningorchestra_tpu.models.base import TrainedModel, as_design
from learningorchestra_tpu.parallel.mesh import DATA_AXIS, MeshRuntime


def _logits(params, X):
    W, b, mu, sigma = (params["W"], params["b"], params["mu"],
                       params["sigma"])
    Xs = ((X - mu) / sigma).astype(jnp.bfloat16)
    return (Xs @ W.astype(jnp.bfloat16)).astype(jnp.float32) + b


def _logits_pre(params, Xs):
    """Logits from a pre-standardized bf16 design matrix (fit path)."""
    return (Xs @ params["W"].astype(jnp.bfloat16)).astype(
        jnp.float32) + params["b"]


def _loss(params, Xs, y, mask, l2):
    logits = _logits_pre(params, Xs)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
    data = jnp.sum(nll * mask) / jnp.sum(mask)
    return data + l2 * jnp.sum(params["W"] ** 2)


def _fit(X, y, n_valid, mu, sigma, *, num_classes, iters, lr, l2, seed):
    """Adam fit = the population program at population one.

    The standalone path and the tune sweep (models/tune.py) MUST share
    one compiled member body: XLA's reduction orders differ between a
    plain and a vmapped lowering of the same arithmetic (the bias
    gradient's row-sum reorders by ~1 ulp/step), and the vmapped program
    is batch-size invariant — so routing the single fit through the
    vmapped body is what makes population members bit-identical to
    standalone fits."""
    params, opt_state = _pop_lr_init(
        jnp.asarray([seed], jnp.int32), mu, sigma, d=X.shape[1],
        num_classes=num_classes)
    mask = (jnp.arange(X.shape[0]) < n_valid).astype(jnp.float32)[None]
    params, _, losses = _fit_pop_adam(
        params, opt_state, X, y, mask, mu, sigma,
        jnp.asarray([lr], jnp.float32), jnp.asarray([l2], jnp.float32),
        jnp.asarray([iters], jnp.int32), jnp.ones((1,), jnp.float32),
        np.int32(0), iters=iters)
    return {k: v[0] for k, v in params.items()}, losses[0]


@jax.jit
def _predict_proba(params, X):
    return jax.nn.softmax(_logits(params, X), axis=-1)


# ---------------------------------------------------------------------------
# Config-population programs (models/tune.py)
# ---------------------------------------------------------------------------

def _pop_adam_tx():
    """The population path's optimizer pair: ``scale_by_adam`` exactly as
    ``optax.adam`` composes it, with the final ``scale(-lr)`` applied
    manually per member so the learning rate can ride as a traced
    per-member scalar. ``(-x)·lr ≡ x·(-lr)`` in IEEE floats, so updates
    are bit-identical to ``optax.adam(lr)``'s."""
    return optax.scale_by_adam()


def _pop_lr_init(seeds, mu, sigma, *, d, num_classes):
    """Stacked per-member init — each member's W is the PRNGKey(seed)
    draw its standalone fit would make (key packing and the normal draw
    are deterministic functions of the seed)."""

    @partial(jax.jit, static_argnames=("d", "num_classes"))
    def init(seeds, mu, sigma, *, d, num_classes):
        def one(seed):
            k = jax.random.PRNGKey(seed)
            return {
                "W": 0.01 * jax.random.normal(k, (d, num_classes),
                                              jnp.float32),
                "b": jnp.zeros((num_classes,), jnp.float32),
                "mu": mu, "sigma": sigma,
            }

        params = jax.vmap(one)(seeds)
        opt_state = jax.vmap(_pop_adam_tx().init)(params)
        return params, opt_state

    return init(seeds, mu, sigma, d=d, num_classes=num_classes)


@partial(jax.jit, static_argnames=("iters",))
def _fit_pop_adam(params, opt_state, X, y, masks, mu, sigma, lrs, l2s,
                  iters_vec, alive, t0, *, iters):
    """One SEGMENT of Adam steps for a POPULATION of lr configs.

    Per member: its own loss mask (validity × fold-train), lr, l2 and
    iteration budget. Global step ``t0 + i`` past a member's
    ``iters_vec`` (or a dead ``alive`` flag) freezes its params and
    optimizer state via ``where`` — the frozen values are exactly the
    standalone fit's final state, so segmenting and halving never
    perturb a surviving member's arithmetic."""
    Xs = ((X - mu) / sigma).astype(jnp.bfloat16)

    def one_member(params, opt_state, mask, lr, l2, it_m, alive_m):
        tx = _pop_adam_tx()

        def step(carry, i):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(_loss)(params, Xs, y, mask,
                                                    l2)
            updates, new_state = tx.update(grads, opt_state)
            new_params = optax.apply_updates(
                params, jax.tree.map(lambda u: u * (-lr), updates))
            act = ((t0 + i) < it_m) & (alive_m > 0)
            params = jax.tree.map(
                lambda a, b: jnp.where(act, a, b), new_params, params)
            opt_state = jax.tree.map(
                lambda a, b: jnp.where(act, a, b), new_state, opt_state)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), jnp.arange(iters))
        return params, opt_state, losses

    return jax.vmap(one_member)(params, opt_state, masks, lrs, l2s,
                                iters_vec, alive)


@partial(jax.jit, static_argnames=("num_classes", "iters", "mesh"))
def _fit_pop_newton(X, y, masks, mu, sigma, l2s, iters_vec, alive, Wz0,
                    t0, *, num_classes, iters, mesh):
    """One SEGMENT of Newton/IRLS steps for a POPULATION of lr configs —
    the serial ``_fit_newton`` body vmapped over members with per-member
    l2 (traced into the ridge), loss masks and step budgets. The shared
    standardized [X | 1] block matrix is built once."""
    C = num_classes
    d = X.shape[1]
    d1 = d + 1

    def shard_fn(X, y, masks, mu, sigma, l2s, iters_vec, alive, Wz0, t0):
        nloc = X.shape[0]
        Z = jnp.concatenate(
            [((X - mu) / sigma), jnp.ones((nloc, 1), jnp.float32)],
            axis=1).astype(jnp.bfloat16)
        blk = min(_NEWTON_BLOCK, nloc)
        nbk = -(-nloc // blk)
        pad = nbk * blk - nloc
        if pad:
            Z = jnp.pad(Z, ((0, pad), (0, 0)))
            y_p = jnp.pad(y, (0, pad))
        else:
            y_p = y

        def one_member(mask, l2, it_m, alive_m, Wz):
            ridge = jnp.tile(jnp.concatenate(
                [jnp.full((d,), 2.0 * l2), jnp.zeros((1,))]), C) + 1e-4
            nf = jnp.maximum(
                jax.lax.psum(mask.sum(), DATA_AXIS), 1.0)
            mask_p = jnp.pad(mask, (0, pad)) if pad else mask

            def step(Wz, i):
                def acc_block(carry, b):
                    g, T1, T2 = carry
                    Zblk = jax.lax.dynamic_slice_in_dim(Z, b * blk, blk)
                    yblk = jax.lax.dynamic_slice_in_dim(y_p, b * blk,
                                                        blk)
                    mblk = jax.lax.dynamic_slice_in_dim(mask_p, b * blk,
                                                        blk)
                    logits = (Zblk @ Wz.astype(jnp.bfloat16)).astype(
                        jnp.float32)
                    Pr = jax.nn.softmax(logits, axis=-1) * mblk[:, None]
                    Y1 = (jax.nn.one_hot(yblk, C, dtype=jnp.float32)
                          * mblk[:, None])
                    R = (Pr - Y1).astype(jnp.bfloat16)
                    g = g + (Zblk.T @ R).astype(jnp.float32)
                    Pb = Pr.astype(jnp.bfloat16)
                    A = (Pb[:, :, None] * Zblk[:, None, :]).reshape(
                        blk, C * d1)
                    T2 = T2 + (A.T @ A).astype(jnp.float32)
                    T1 = T1 + jnp.stack([
                        (Zblk.T @ (Zblk * Pb[:, c:c + 1])).astype(
                            jnp.float32)
                        for c in range(C)])
                    return (g, T1, T2), None

                (g, T1, T2), _ = jax.lax.scan(
                    acc_block,
                    (jnp.zeros((d1, C), jnp.float32),
                     jnp.zeros((C, d1, d1), jnp.float32),
                     jnp.zeros((C * d1, C * d1), jnp.float32)),
                    jnp.arange(nbk))
                g, T1, T2 = jax.lax.psum((g, T1, T2), DATA_AXIS)
                gflat = (g.T.reshape(C * d1) / nf
                         + ridge * Wz.T.reshape(C * d1))
                H = jax.scipy.linalg.block_diag(
                    *[T1[c] for c in range(C)]) - T2
                H = H / nf + jnp.diag(ridge)
                delta = jnp.linalg.solve(H, gflat)
                norm = jnp.linalg.norm(delta)
                delta = delta * jnp.minimum(
                    1.0, 5.0 / jnp.maximum(norm, 1e-12))
                delta = jnp.where(jnp.isfinite(delta), delta, 0.0)
                act = ((t0 + i) < it_m) & (alive_m > 0)
                return jnp.where(act, Wz - delta.reshape(C, d1).T, Wz), \
                    None

            Wz, _ = jax.lax.scan(step, Wz, jnp.arange(iters))
            return Wz

        # lax.map, NOT vmap: the Hessian accumulation is bf16 matmuls,
        # and XLA tiles a BATCHED bf16 contraction differently at every
        # batch width — vmapped members drift ~1e-3 from their standalone
        # fits and even from themselves at other population sizes. A
        # scan over members runs the one unbatched member program per
        # config, which is what makes population newton bit-identical to
        # serial newton. Members are large-matmul-bound, so serializing
        # them costs little against the shared-compile/shared-data win.
        return jax.lax.map(
            lambda args: one_member(*args),
            (masks, l2s, iters_vec, alive, Wz0))

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(None, DATA_AXIS), P(),
                  P(), P(), P(), P(), P(), P()),
        out_specs=P(), check_vma=False,
    )(X, y, masks, mu, sigma, l2s, iters_vec, alive, Wz0, t0)


@partial(jax.jit, static_argnames=("mesh",))
def _pop_lr_scores(W, b, mu, sigma, X, y, ew_pop, *, mesh):
    """Per-member lr accuracy on per-member (eval-fold) row weights."""

    def shard_fn(W, b, mu, sigma, X, y, ew_pop):
        Xs = ((X - mu) / sigma).astype(jnp.bfloat16)

        def one_member(W_m, b_m, ew):
            logits = (Xs @ W_m.astype(jnp.bfloat16)).astype(
                jnp.float32) + b_m
            pred = jnp.argmax(logits, axis=1).astype(y.dtype)
            hit = jax.lax.psum(
                ((pred == y).astype(jnp.float32) * ew).sum(), DATA_AXIS)
            tot = jax.lax.psum(ew.sum(), DATA_AXIS)
            return hit / jnp.maximum(tot, 1.0)

        return jax.vmap(one_member)(W, b, ew_pop)

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(DATA_AXIS), P(DATA_AXIS),
                  P(None, DATA_AXIS)),
        out_specs=P(), check_vma=False,
    )(W, b, mu, sigma, X, y, ew_pop)


#: Rows per Newton accumulation block (bounds the lane-padded transient
#: tensors: a (B, C·(d+1)) bf16 block at B=2^20, C·(d+1)=58 is ~120 MB).
_NEWTON_BLOCK = 1 << 20
#: Newton applies while the Hessian side C·(d+1) stays this small — the
#: (C·(d+1))² solve is negligible and the per-block A tensor bounded.
_NEWTON_MAX_CD = 256


@partial(jax.jit, static_argnames=("num_classes", "iters", "mesh"))
def _fit_newton(X, y, n_valid, mu, sigma, *, num_classes, iters, l2, mesh):
    """Exact multinomial-Newton (IRLS) fit, row-blocked per shard.

    Z = [standardized X | 1] in bf16; per step each data-axis shard scans
    its row blocks accumulating g = Z'(P−Y) and the exact Hessian
    H[(c,i),(c',j)] = Σ_n z_i z_j p_c (δ_cc' − p_c'), one ``psum`` reduces
    both over ICI, and a replicated dense solve updates the (d+1, C)
    augmented weights. Quadratic convergence: ~20 steps replace hundreds
    of first-order passes over the data.
    """
    C = num_classes
    d = X.shape[1]
    d1 = d + 1
    # l2 penalizes weights, not the intercept row (sklearn/Spark parity).
    # The ε term regularizes the softmax shift-null direction of H; it must
    # dominate the bf16 noise floor of the accumulated Hessian (~1e-3
    # relative), else the solve blows up along the null space.
    ridge = jnp.tile(jnp.concatenate(
        [jnp.full((d,), 2.0 * l2), jnp.zeros((1,))]), C) + 1e-4

    def shard_fn(X, y, n_valid, mu, sigma):
        nloc = X.shape[0]
        start = jax.lax.axis_index(DATA_AXIS) * nloc
        mask = ((start + jnp.arange(nloc)) < n_valid).astype(jnp.float32)
        Z = jnp.concatenate(
            [((X - mu) / sigma), jnp.ones((nloc, 1), jnp.float32)],
            axis=1).astype(jnp.bfloat16)                   # (nloc, d+1)
        blk = min(_NEWTON_BLOCK, nloc)
        nbk = -(-nloc // blk)
        pad = nbk * blk - nloc
        if pad:
            Z = jnp.pad(Z, ((0, pad), (0, 0)))
            y = jnp.pad(y, (0, pad))
            mask = jnp.pad(mask, (0, pad))
        nf = jnp.maximum(n_valid.astype(jnp.float32), 1.0)

        def step(Wz, _):
            # Index scan + dynamic_slice per block: scanning over a stacked
            # (nbk, blk, d1) operand compiles ~30x slower on XLA:TPU at
            # these block sizes (minutes for the whole fit).
            def acc_block(carry, i):
                g, T1, T2 = carry
                Zblk = jax.lax.dynamic_slice_in_dim(Z, i * blk, blk)
                yblk = jax.lax.dynamic_slice_in_dim(y, i * blk, blk)
                mblk = jax.lax.dynamic_slice_in_dim(mask, i * blk, blk)
                logits = (Zblk @ Wz.astype(jnp.bfloat16)).astype(
                    jnp.float32)
                Pr = jax.nn.softmax(logits, axis=-1) * mblk[:, None]
                Y1 = (jax.nn.one_hot(yblk, C, dtype=jnp.float32)
                      * mblk[:, None])
                R = (Pr - Y1).astype(jnp.bfloat16)
                g = g + (Zblk.T @ R).astype(jnp.float32)      # (d1, C)
                Pb = Pr.astype(jnp.bfloat16)
                A = (Pb[:, :, None] * Zblk[:, None, :]).reshape(
                    blk, C * d1)
                T2 = T2 + (A.T @ A).astype(jnp.float32)       # (Cd1, Cd1)
                T1 = T1 + jnp.stack([
                    (Zblk.T @ (Zblk * Pb[:, c:c + 1])).astype(jnp.float32)
                    for c in range(C)])                       # (C, d1, d1)
                return (g, T1, T2), None

            (g, T1, T2), _ = jax.lax.scan(
                acc_block,
                (jnp.zeros((d1, C), jnp.float32),
                 jnp.zeros((C, d1, d1), jnp.float32),
                 jnp.zeros((C * d1, C * d1), jnp.float32)),
                jnp.arange(nbk))
            g, T1, T2 = jax.lax.psum((g, T1, T2), DATA_AXIS)  # ICI reduce
            gflat = g.T.reshape(C * d1) / nf + ridge * Wz.T.reshape(C * d1)
            H = jax.scipy.linalg.block_diag(
                *[T1[c] for c in range(C)]) - T2
            H = H / nf + jnp.diag(ridge)
            delta = jnp.linalg.solve(H, gflat)
            # Trust region: on separable data the saturated Hessian
            # vanishes and an uncapped Newton step overshoots to NaN.
            # Near the optimum steps are tiny, so the cap never binds.
            norm = jnp.linalg.norm(delta)
            delta = delta * jnp.minimum(1.0, 5.0 / jnp.maximum(norm, 1e-12))
            delta = jnp.where(jnp.isfinite(delta), delta, 0.0)
            return Wz - delta.reshape(C, d1).T, None

        Wz, _ = jax.lax.scan(step, jnp.zeros((d1, C), jnp.float32), None,
                             length=iters)
        return Wz

    Wz = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P(), P()),
        out_specs=P(), check_vma=False,
    )(X, y, n_valid, mu, sigma)
    return {"W": Wz[:d], "b": Wz[d], "mu": mu, "sigma": sigma}


@partial(jax.jit, static_argnames=("mesh",))
def _device_stats(X, n_valid, *, mesh):
    """Per-feature mean/std on the already-sharded design matrix — two
    host passes over gigabytes become two device reductions (masked sums
    psum over the data axis; ~ms instead of seconds per fit).

    Two-pass: mean first, then Σ(x−μ)². The one-pass E[x²]−E[x]² form
    catastrophically cancels in f32 for features with |mean| ≫ std (a
    year/price column would come out with garbage variance and silently
    enter the solver unstandardized)."""
    from jax.sharding import PartitionSpec as P

    def shard_fn(X, n_valid):
        nloc = X.shape[0]
        start = jax.lax.axis_index(DATA_AXIS) * nloc
        m = ((start + jnp.arange(nloc)) < n_valid).astype(jnp.float32)
        nf = jnp.maximum(n_valid.astype(jnp.float32), 1.0)
        s1 = jax.lax.psum((X * m[:, None]).sum(axis=0), DATA_AXIS)
        mu = s1 / nf
        d = (X - mu) * m[:, None]
        s2 = jax.lax.psum((d * d).sum(axis=0), DATA_AXIS)
        return mu, s2 / nf

    mu, var = jax.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(DATA_AXIS), P()),
        out_specs=(P(), P()), check_vma=False,
    )(X, n_valid)
    sigma = jnp.sqrt(jnp.maximum(var, 0.0))
    return mu, jnp.where(sigma < 1e-7, 1.0, sigma)


def fit(runtime: MeshRuntime, X: np.ndarray, y: np.ndarray,
        num_classes: int, seed: int = 0, *, iters: int = 300,
        lr: float = 0.1, l2: float = 1e-4,
        solver: str = "auto") -> TrainedModel:

    X = as_design(X)
    X_dev, n = runtime.shard_rows(X)
    y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
    n_dev = runtime.replicate(np.int32(n))
    mu, sigma = _device_stats(X_dev, n_dev, mesh=runtime.mesh)
    if solver == "auto":
        solver = ("newton"
                  if num_classes * (X.shape[1] + 1) <= _NEWTON_MAX_CD
                  else "adam")
    if solver == "newton":
        params = _fit_newton(
            X_dev, y_dev, n_dev, mu, sigma,
            num_classes=num_classes, iters=min(iters, 20), l2=l2,
            mesh=runtime.mesh)
    elif solver == "adam":
        params, _ = _fit(X_dev, y_dev, n_dev, mu, sigma,
                         num_classes=num_classes, iters=iters, lr=lr, l2=l2,
                         seed=seed)
    else:
        raise ValueError(f"unknown lr solver {solver!r}")
    return TrainedModel(kind="lr", params=params,
                        predict_proba_fn=_predict_proba,
                        num_classes=num_classes,
                        hparams={"iters": iters, "lr": lr, "l2": l2,
                                 "solver": solver})
