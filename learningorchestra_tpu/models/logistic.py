"""Logistic regression trainer ("lr" in the classifier registry).

The reference's "lr" is ``pyspark.ml.classification.LogisticRegression``
fitted as a distributed iterative Spark job (reference model_builder.py:152,
200). TPU-native design: multinomial logistic regression as one jit-compiled
program. Two solvers:

- **Newton/IRLS** (default whenever ``C·(d+1)`` is small enough for the
  Hessian solve): ~20 second-order steps instead of hundreds of
  first-order ones. Each step is a ``lax.scan`` over row blocks that
  accumulates the gradient and the exact multinomial Hessian with MXU
  contractions — blocking matters because any (n, C<128)-shaped
  intermediate lane-pads to 128 on TPU, so full-batch softmax/residual
  tensors would each cost gigabytes of HBM traffic at 11M rows.
- **Adam scan** (wide-model fallback): full-batch first-order steps on the
  bf16 design matrix.

Rows are sharded across the mesh data axis; losses/moments are masked
means, so their contractions over the sharded row dimension make XLA
insert the ICI all-reduce automatically (no hand-written collectives).
bfloat16 matmuls feed the MXU; parameters stay float32.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from learningorchestra_tpu.models.base import TrainedModel, as_design
from learningorchestra_tpu.parallel.mesh import DATA_AXIS, MeshRuntime


def _logits(params, X):
    W, b, mu, sigma = (params["W"], params["b"], params["mu"],
                       params["sigma"])
    Xs = ((X - mu) / sigma).astype(jnp.bfloat16)
    return (Xs @ W.astype(jnp.bfloat16)).astype(jnp.float32) + b


def _logits_pre(params, Xs):
    """Logits from a pre-standardized bf16 design matrix (fit path)."""
    return (Xs @ params["W"].astype(jnp.bfloat16)).astype(
        jnp.float32) + params["b"]


def _loss(params, Xs, y, mask, l2):
    logits = _logits_pre(params, Xs)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
    data = jnp.sum(nll * mask) / jnp.sum(mask)
    return data + l2 * jnp.sum(params["W"] ** 2)


@partial(jax.jit, static_argnames=("num_classes", "iters"))
def _fit(X, y, n_valid, mu, sigma, *, num_classes, iters, lr, l2, seed):
    n, d = X.shape
    k = jax.random.PRNGKey(seed)
    params = {
        "W": 0.01 * jax.random.normal(k, (d, num_classes), jnp.float32),
        "b": jnp.zeros((num_classes,), jnp.float32),
        "mu": mu, "sigma": sigma,
    }
    # Standardize + bf16-cast ONCE before the scan: every Adam iteration
    # then reads the half-size matrix instead of re-deriving it (the fit
    # is HBM-bandwidth-bound, so this halves the per-iteration traffic).
    Xs = ((X - mu) / sigma).astype(jnp.bfloat16)
    mask = (jnp.arange(n) < n_valid).astype(jnp.float32)
    opt = optax.adam(lr)
    opt_state = opt.init(params)

    def step(carry, _):
        params, opt_state = carry
        loss, grads = jax.value_and_grad(_loss)(params, Xs, y, mask, l2)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    (params, _), losses = jax.lax.scan(step, (params, opt_state), None,
                                       length=iters)
    return params, losses


@jax.jit
def _predict_proba(params, X):
    return jax.nn.softmax(_logits(params, X), axis=-1)


#: Rows per Newton accumulation block (bounds the lane-padded transient
#: tensors: a (B, C·(d+1)) bf16 block at B=2^20, C·(d+1)=58 is ~120 MB).
_NEWTON_BLOCK = 1 << 20
#: Newton applies while the Hessian side C·(d+1) stays this small — the
#: (C·(d+1))² solve is negligible and the per-block A tensor bounded.
_NEWTON_MAX_CD = 256


@partial(jax.jit, static_argnames=("num_classes", "iters", "mesh"))
def _fit_newton(X, y, n_valid, mu, sigma, *, num_classes, iters, l2, mesh):
    """Exact multinomial-Newton (IRLS) fit, row-blocked per shard.

    Z = [standardized X | 1] in bf16; per step each data-axis shard scans
    its row blocks accumulating g = Z'(P−Y) and the exact Hessian
    H[(c,i),(c',j)] = Σ_n z_i z_j p_c (δ_cc' − p_c'), one ``psum`` reduces
    both over ICI, and a replicated dense solve updates the (d+1, C)
    augmented weights. Quadratic convergence: ~20 steps replace hundreds
    of first-order passes over the data.
    """
    C = num_classes
    d = X.shape[1]
    d1 = d + 1
    # l2 penalizes weights, not the intercept row (sklearn/Spark parity).
    # The ε term regularizes the softmax shift-null direction of H; it must
    # dominate the bf16 noise floor of the accumulated Hessian (~1e-3
    # relative), else the solve blows up along the null space.
    ridge = jnp.tile(jnp.concatenate(
        [jnp.full((d,), 2.0 * l2), jnp.zeros((1,))]), C) + 1e-4

    def shard_fn(X, y, n_valid, mu, sigma):
        nloc = X.shape[0]
        start = jax.lax.axis_index(DATA_AXIS) * nloc
        mask = ((start + jnp.arange(nloc)) < n_valid).astype(jnp.float32)
        Z = jnp.concatenate(
            [((X - mu) / sigma), jnp.ones((nloc, 1), jnp.float32)],
            axis=1).astype(jnp.bfloat16)                   # (nloc, d+1)
        blk = min(_NEWTON_BLOCK, nloc)
        nbk = -(-nloc // blk)
        pad = nbk * blk - nloc
        if pad:
            Z = jnp.pad(Z, ((0, pad), (0, 0)))
            y = jnp.pad(y, (0, pad))
            mask = jnp.pad(mask, (0, pad))
        nf = jnp.maximum(n_valid.astype(jnp.float32), 1.0)

        def step(Wz, _):
            # Index scan + dynamic_slice per block: scanning over a stacked
            # (nbk, blk, d1) operand compiles ~30x slower on XLA:TPU at
            # these block sizes (minutes for the whole fit).
            def acc_block(carry, i):
                g, T1, T2 = carry
                Zblk = jax.lax.dynamic_slice_in_dim(Z, i * blk, blk)
                yblk = jax.lax.dynamic_slice_in_dim(y, i * blk, blk)
                mblk = jax.lax.dynamic_slice_in_dim(mask, i * blk, blk)
                logits = (Zblk @ Wz.astype(jnp.bfloat16)).astype(
                    jnp.float32)
                Pr = jax.nn.softmax(logits, axis=-1) * mblk[:, None]
                Y1 = (jax.nn.one_hot(yblk, C, dtype=jnp.float32)
                      * mblk[:, None])
                R = (Pr - Y1).astype(jnp.bfloat16)
                g = g + (Zblk.T @ R).astype(jnp.float32)      # (d1, C)
                Pb = Pr.astype(jnp.bfloat16)
                A = (Pb[:, :, None] * Zblk[:, None, :]).reshape(
                    blk, C * d1)
                T2 = T2 + (A.T @ A).astype(jnp.float32)       # (Cd1, Cd1)
                T1 = T1 + jnp.stack([
                    (Zblk.T @ (Zblk * Pb[:, c:c + 1])).astype(jnp.float32)
                    for c in range(C)])                       # (C, d1, d1)
                return (g, T1, T2), None

            (g, T1, T2), _ = jax.lax.scan(
                acc_block,
                (jnp.zeros((d1, C), jnp.float32),
                 jnp.zeros((C, d1, d1), jnp.float32),
                 jnp.zeros((C * d1, C * d1), jnp.float32)),
                jnp.arange(nbk))
            g, T1, T2 = jax.lax.psum((g, T1, T2), DATA_AXIS)  # ICI reduce
            gflat = g.T.reshape(C * d1) / nf + ridge * Wz.T.reshape(C * d1)
            H = jax.scipy.linalg.block_diag(
                *[T1[c] for c in range(C)]) - T2
            H = H / nf + jnp.diag(ridge)
            delta = jnp.linalg.solve(H, gflat)
            # Trust region: on separable data the saturated Hessian
            # vanishes and an uncapped Newton step overshoots to NaN.
            # Near the optimum steps are tiny, so the cap never binds.
            norm = jnp.linalg.norm(delta)
            delta = delta * jnp.minimum(1.0, 5.0 / jnp.maximum(norm, 1e-12))
            delta = jnp.where(jnp.isfinite(delta), delta, 0.0)
            return Wz - delta.reshape(C, d1).T, None

        Wz, _ = jax.lax.scan(step, jnp.zeros((d1, C), jnp.float32), None,
                             length=iters)
        return Wz

    Wz = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P(), P()),
        out_specs=P(), check_vma=False,
    )(X, y, n_valid, mu, sigma)
    return {"W": Wz[:d], "b": Wz[d], "mu": mu, "sigma": sigma}


@partial(jax.jit, static_argnames=("mesh",))
def _device_stats(X, n_valid, *, mesh):
    """Per-feature mean/std on the already-sharded design matrix — two
    host passes over gigabytes become two device reductions (masked sums
    psum over the data axis; ~ms instead of seconds per fit).

    Two-pass: mean first, then Σ(x−μ)². The one-pass E[x²]−E[x]² form
    catastrophically cancels in f32 for features with |mean| ≫ std (a
    year/price column would come out with garbage variance and silently
    enter the solver unstandardized)."""
    from jax.sharding import PartitionSpec as P

    def shard_fn(X, n_valid):
        nloc = X.shape[0]
        start = jax.lax.axis_index(DATA_AXIS) * nloc
        m = ((start + jnp.arange(nloc)) < n_valid).astype(jnp.float32)
        nf = jnp.maximum(n_valid.astype(jnp.float32), 1.0)
        s1 = jax.lax.psum((X * m[:, None]).sum(axis=0), DATA_AXIS)
        mu = s1 / nf
        d = (X - mu) * m[:, None]
        s2 = jax.lax.psum((d * d).sum(axis=0), DATA_AXIS)
        return mu, s2 / nf

    mu, var = jax.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(DATA_AXIS), P()),
        out_specs=(P(), P()), check_vma=False,
    )(X, n_valid)
    sigma = jnp.sqrt(jnp.maximum(var, 0.0))
    return mu, jnp.where(sigma < 1e-7, 1.0, sigma)


def fit(runtime: MeshRuntime, X: np.ndarray, y: np.ndarray,
        num_classes: int, seed: int = 0, *, iters: int = 300,
        lr: float = 0.1, l2: float = 1e-4,
        solver: str = "auto") -> TrainedModel:

    X = as_design(X)
    X_dev, n = runtime.shard_rows(X)
    y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
    n_dev = runtime.replicate(np.int32(n))
    mu, sigma = _device_stats(X_dev, n_dev, mesh=runtime.mesh)
    if solver == "auto":
        solver = ("newton"
                  if num_classes * (X.shape[1] + 1) <= _NEWTON_MAX_CD
                  else "adam")
    if solver == "newton":
        params = _fit_newton(
            X_dev, y_dev, n_dev, mu, sigma,
            num_classes=num_classes, iters=min(iters, 20), l2=l2,
            mesh=runtime.mesh)
    elif solver == "adam":
        params, _ = _fit(X_dev, y_dev, n_dev, mu, sigma,
                         num_classes=num_classes, iters=iters, lr=lr, l2=l2,
                         seed=seed)
    else:
        raise ValueError(f"unknown lr solver {solver!r}")
    return TrainedModel(kind="lr", params=params,
                        predict_proba_fn=_predict_proba,
                        num_classes=num_classes,
                        hparams={"iters": iters, "lr": lr, "l2": l2,
                                 "solver": solver})
