"""Logistic regression trainer ("lr" in the classifier registry).

The reference's "lr" is ``pyspark.ml.classification.LogisticRegression``
fitted as a distributed iterative Spark job (reference model_builder.py:152,
200). TPU-native design: multinomial logistic regression as one jit-compiled
program — a ``lax.scan`` over full-batch Adam steps on the standardized
design matrix. Rows are sharded across the mesh data axis; the loss is a
masked mean, so its gradient contracts over the sharded row dimension and
XLA inserts the ICI all-reduce automatically (no hand-written collectives).
bfloat16 matmuls feed the MXU; parameters stay float32.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learningorchestra_tpu.models.base import TrainedModel
from learningorchestra_tpu.parallel.mesh import MeshRuntime


def _logits(params, X):
    W, b, mu, sigma = (params["W"], params["b"], params["mu"],
                       params["sigma"])
    Xs = ((X - mu) / sigma).astype(jnp.bfloat16)
    return (Xs @ W.astype(jnp.bfloat16)).astype(jnp.float32) + b


def _logits_pre(params, Xs):
    """Logits from a pre-standardized bf16 design matrix (fit path)."""
    return (Xs @ params["W"].astype(jnp.bfloat16)).astype(
        jnp.float32) + params["b"]


def _loss(params, Xs, y, mask, l2):
    logits = _logits_pre(params, Xs)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
    data = jnp.sum(nll * mask) / jnp.sum(mask)
    return data + l2 * jnp.sum(params["W"] ** 2)


@partial(jax.jit, static_argnames=("num_classes", "iters"))
def _fit(X, y, n_valid, mu, sigma, *, num_classes, iters, lr, l2, seed):
    n, d = X.shape
    k = jax.random.PRNGKey(seed)
    params = {
        "W": 0.01 * jax.random.normal(k, (d, num_classes), jnp.float32),
        "b": jnp.zeros((num_classes,), jnp.float32),
        "mu": mu, "sigma": sigma,
    }
    # Standardize + bf16-cast ONCE before the scan: every Adam iteration
    # then reads the half-size matrix instead of re-deriving it (the fit
    # is HBM-bandwidth-bound, so this halves the per-iteration traffic).
    Xs = ((X - mu) / sigma).astype(jnp.bfloat16)
    mask = (jnp.arange(n) < n_valid).astype(jnp.float32)
    opt = optax.adam(lr)
    opt_state = opt.init(params)

    def step(carry, _):
        params, opt_state = carry
        loss, grads = jax.value_and_grad(_loss)(params, Xs, y, mask, l2)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    (params, _), losses = jax.lax.scan(step, (params, opt_state), None,
                                       length=iters)
    return params, losses


@jax.jit
def _predict_proba(params, X):
    return jax.nn.softmax(_logits(params, X), axis=-1)


def _standardization_stats(X: np.ndarray):
    mu = X.mean(axis=0)
    sigma = X.std(axis=0)
    sigma = np.where(sigma < 1e-7, 1.0, sigma)
    return mu.astype(np.float32), sigma.astype(np.float32)


def fit(runtime: MeshRuntime, X: np.ndarray, y: np.ndarray,
        num_classes: int, seed: int = 0, *, iters: int = 300,
        lr: float = 0.1, l2: float = 1e-4) -> TrainedModel:
    X = np.asarray(X, np.float32)
    mu, sigma = _standardization_stats(X)
    X_dev, n = runtime.shard_rows(X)
    y_dev, _ = runtime.shard_rows(np.asarray(y, np.int32))
    params, _ = _fit(X_dev, y_dev, runtime.replicate(np.int32(n)),
                     runtime.replicate(mu), runtime.replicate(sigma),
                     num_classes=num_classes, iters=iters, lr=lr, l2=l2,
                     seed=seed)
    return TrainedModel(kind="lr", params=params,
                        predict_proba_fn=_predict_proba,
                        num_classes=num_classes,
                        hparams={"iters": iters, "lr": lr, "l2": l2})
