"""Ahead-of-time-compiled online predict programs (the serving tier's
device side).

The batch predict path (`ModelBuilder.predict`) re-traces and re-jits per
dataset shape and pays a host→mesh shard per call — fine for minutes-long
dataset jobs, fatal for request/response serving where the whole latency
budget is milliseconds. Here every trained model gets a small set of
predict programs compiled ONCE at model load, bucketed by padded batch
size (1/8/64/…/max_batch), so a micro-batch of any size ≤ max_batch
dispatches a pre-compiled XLA executable with zero trace/compile work on
the hot path — the same static-shape discipline the fit programs use,
applied to serving.

Design points:

- **AOT, not lazy jit**: ``jax.jit(...).lower(params, x_spec).compile()``
  at load time. The first request never eats a compile; a model's whole
  bucket ladder is built before it serves.
- **Bucketed padding**: requests coalesce into batches padded up to the
  next bucket. Few buckets keep compile count bounded; padding rows are
  zeros and sliced off the output (per-row programs mask nothing —
  every family's predict is row-local, so pad rows cannot perturb real
  rows).
- **Per-device replicas, not mesh shards**: micro-batches (≤ a few
  hundred rows) cannot amortize a mesh shard, and single-device
  programs carry no collectives — so the online tier is safe
  per-process even on a multi-process pod (no SPMD dispatch scope
  needed; contrast ``MeshRuntime.shard_rows``). ``serve_replicas``
  (``LO_TPU_SERVE_REPLICAS``) replicates the whole bucket ladder
  across N local devices instead: params ``device_put`` to each
  replica's device, one compiled ladder per device, every replica
  bit-identical by the row-wise-evaluation argument below. The default
  (1) preserves the single-device topology byte-for-byte; 0 means all
  local devices.
- **Donated inputs**: the batch buffer is donated to the executable
  where the backend supports it (TPU/GPU), so dispatch writes the
  output into the input's HBM pages instead of allocating per request.
  CPU has no donation — gated to keep the test rig warning-free.
- **Versioned cache**: programs are keyed (model name, version, bucket)
  where version is the manifest file's (mtime_ns, size). Re-saving a
  model under the same name (incremental refit, ROADMAP item 4) or
  deleting it invalidates automatically on the next entry lookup.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from learningorchestra_tpu.config import Settings, settings as global_settings
from learningorchestra_tpu.models.persistence import ModelRegistry
from learningorchestra_tpu.models.registry import ONLINE_KINDS
from learningorchestra_tpu.utils import failpoints, resources

#: Chaos seam before a model's bucket-ladder load+compile — raise-mode
#: proves a failed cold load surfaces as the request's error (never a
#: half-cached entry), slow/hang-mode that compile stalls block only the
#: loading model's requests (per-name lock, docs/fault_tolerance.md §7).
FP_PRE_COMPILE = failpoints.declare("serving.aot.pre_compile")


def resolve_replicas(cfg: Settings) -> int:
    """How many device replicas the online predict plane runs
    (``serve_replicas``): 1 — the default — is today's single-device
    topology, kept byte-for-byte; 0 means one replica per local device;
    any other N clamps to the locally addressable device count (never
    the global pod list — other processes' devices are not addressable
    from here)."""
    n = int(cfg.serve_replicas)
    if n == 1:
        return 1
    import jax

    avail = max(1, len(jax.local_devices()))
    return avail if n <= 0 else min(n, avail)


def predict_buckets(max_batch: int) -> Tuple[int, ...]:
    """The padded-batch-size ladder: powers of 8 up to ``max_batch``,
    which is always itself a bucket (1, 8, 64, 256 for the default 256).
    Geometric spacing bounds both the compile count (log_8) and the
    worst-case padding waste (<8x, and real micro-batches cluster near
    the coalesced size anyway)."""
    max_batch = max(1, int(max_batch))
    out: List[int] = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 8
    out.append(max_batch)
    return tuple(out)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _numeric_column(field: str, values: List[Any]) -> np.ndarray:
    """Column-ize one numeric field of inline rows (None → NaN so fitted
    fillna stats apply). Strings are rejected rather than silently
    fitted a fresh vocab: the model has no encoding for this field, and
    letting ``apply_steps`` invent one would both answer garbage and
    write into the SHARED fitted state from a request thread."""
    try:
        return np.array([np.nan if v is None else float(v)
                         for v in values], dtype=np.float64)
    except (TypeError, ValueError):
        raise ValueError(
            f"field {field!r} is numeric for this model; got "
            "non-numeric values") from None


def design_from_rows(rows: Sequence[Any], pp: Dict[str, Any]) -> np.ndarray:
    """Inline JSON feature rows → the model's design matrix, with its
    train-time preprocessing state applied.

    Two row forms:

    - list of objects ``{field: value}`` — raw source fields; the fitted
      pipeline (label-encode vocabs, fillna statistics, standardize
      stats) applies exactly as ``ModelBuilder.predict`` applies it to a
      stored dataset. A field the fitted vocab knows is forced to the
      object dtype (so numbers sent for a train-time string column still
      hit the vocab), everything else is numeric.
    - list of lists — already-assembled design rows in
      ``feature_fields`` order (the zero-copy fast path for callers that
      preprocess client-side).
    - a 2-D ``np.ndarray`` — rows already decoded from a binary columnar
      request body (serving/rowchannel.py): same width/finiteness
      validation as list rows with ZERO per-row decode — the buffer the
      socket delivered is the design matrix.
    """
    from learningorchestra_tpu.ops.preprocess import apply_steps

    if isinstance(rows, np.ndarray):
        feature_fields = list(pp["feature_fields"])
        if rows.ndim != 2 or rows.shape[0] == 0:
            raise ValueError(
                "columnar rows must be a non-empty 2-D matrix")
        if rows.shape[1] != len(feature_fields):
            raise ValueError(
                f"columnar rows must be shaped (n, {len(feature_fields)}) "
                f"matching feature_fields {feature_fields}")
        X = np.asarray(rows, dtype=np.float32)
        return _finite_design(np.ascontiguousarray(X), feature_fields)
    if not isinstance(rows, (list, tuple)) or not rows:
        raise ValueError("rows must be a non-empty JSON array")
    feature_fields = list(pp["feature_fields"])
    if not isinstance(rows[0], dict):
        try:
            X = np.asarray(rows, dtype=np.float32)
        except (TypeError, ValueError):
            # Non-numeric elements (dicts mixed into list rows, strings,
            # nested objects) must 406 like every other malformed body,
            # not surface numpy's TypeError as a 500.
            raise ValueError(
                "list rows must contain only numeric values") from None
        if X.ndim != 2 or X.shape[1] != len(feature_fields):
            raise ValueError(
                f"list rows must be shaped (n, {len(feature_fields)}) "
                f"matching feature_fields {feature_fields}")
        return _finite_design(np.ascontiguousarray(X), feature_fields)

    if not all(isinstance(r, dict) for r in rows):
        raise ValueError("rows must be all objects or all lists")
    # Empty steps means the default pipeline — ``design_matrix`` defaults
    # it internally, so persisted manifests carry [] and the fitted state
    # keys ("0:label_encode", …) only line up once we default the same
    # way.
    from learningorchestra_tpu.ops.preprocess import _DEFAULT_STEPS

    steps = pp["steps"] or list(_DEFAULT_STEPS)
    # The fitted state is shared READ-ONLY across concurrent requests —
    # no per-request copy (a deepcopy of a 100k-entry vocab would
    # dominate single-row predicts). Safe because the column coercion
    # below guarantees apply_steps never has a statistic to fit: fields
    # the fitted vocabs know arrive as object/string columns, every
    # other field arrives numeric-or-406, and every fitted step carries
    # its state key, so all step branches reduce to pure application.
    state = pp["state"]
    vocab_fields = set()
    for key, val in state.items():
        if ":label_encode" in str(key) and isinstance(val, dict):
            vocab_fields.update(val.keys())
    fields: List[str] = []
    for r in rows:
        for f in r:
            if f not in fields:
                fields.append(f)
    label = pp.get("label")
    # Only the columns the design needs: feature fields plus any field
    # the fitted vocabs encode. Extra payload fields (a Name column, a
    # request id) are ignored, matching the batch path's tolerance of
    # non-feature columns — rejecting them would 406 every client that
    # sends its full raw record.
    needed = set(feature_fields) | vocab_fields
    cols: Dict[str, np.ndarray] = {}
    for f in fields:
        if f == label or f not in needed:
            continue                      # label / non-feature payload
        values = [r.get(f) for r in rows]
        if f in vocab_fields:
            # Train-time string column: route through the fitted vocab
            # (unknown values encode to len(vocab), same as the batch
            # path's apply-to-test semantics).
            cols[f] = np.array(
                [None if v is None else str(v) for v in values],
                dtype=object)
        else:
            cols[f] = _numeric_column(f, values)
    out, _ = apply_steps(cols, steps, state)
    missing = [f for f in feature_fields if f not in out]
    if missing:
        raise ValueError(
            f"rows missing model feature fields: {missing}")
    return _finite_design(np.stack(
        [np.asarray(out[f], np.float32) for f in feature_fields], axis=1),
        feature_fields)


def _finite_design(X: np.ndarray, feature_fields: List[str]) -> np.ndarray:
    """Reject rows whose design values are non-finite AFTER the fitted
    pipeline ran — e.g. a null sent for a field that had no missing
    values at train time, so no fill statistic was ever fitted. The
    batch path would silently propagate the NaN into NaN probabilities
    (caught live during verification); online serving answers an
    explicit 406 naming the field instead."""
    finite = np.isfinite(X)
    if not finite.all():
        bad = ~finite
        bad_rows = np.where(bad.any(axis=1))[0]
        bad_fields = [feature_fields[j]
                      for j in np.where(bad.any(axis=0))[0]]
        raise ValueError(
            f"rows {bad_rows[:5].tolist()} have non-finite features "
            f"after preprocessing (fields {bad_fields}); the model was "
            "fitted with no fill statistic for them — send finite "
            "values or refit with NaNs present")
    return X


class AotModel:
    """One loaded trained model + its compiled bucket ladder.

    Compilation happens once, in ``__init__`` (model load) — never on
    the request path. ``predict`` pads a host batch up to its bucket,
    runs the compiled executable on the serving device, and slices the
    padding back off.
    """

    def __init__(self, name: str, version: Tuple[int, int],
                 manifest: Dict[str, Any], model,
                 buckets: Sequence[int], replicas: int = 1):
        import jax
        import jax.numpy as jnp

        if manifest["kind"] not in ONLINE_KINDS:
            raise ValueError(
                f"model kind {manifest['kind']!r} is not servable online "
                f"(supported: {list(ONLINE_KINDS)})")
        pp = manifest.get("preprocess")
        if pp is None:
            raise ValueError(
                f"model {name} was exec-preprocessed; it carries no "
                "reproducible preprocessing state to apply to request rows")
        self.name = name
        self.version = version
        self.manifest = manifest
        self.preprocess = pp
        self.kind = manifest["kind"]
        self.buckets = tuple(buckets)
        self.n_features = len(pp["feature_fields"])
        #: Swap-epoch token stamped by the cache on insert: strictly
        #: increasing per model name across rebuilds, so any response
        #: evaluated through this entry is attributable to exactly one
        #: version-swap generation (the mesh-epoch discipline applied to
        #: the registry version token). 0 until the cache stamps it.
        self.swap_epoch = 0
        # local_devices, not devices: after jax.distributed init the
        # global list leads with the coordinator's devices, which are
        # non-addressable from other pod processes — each process must
        # pin its online tier to devices it owns. Replica i is pinned to
        # local device i; replicas beyond the local device count would
        # double up on a device for zero parallelism, so they clamp.
        local = jax.local_devices()
        self.n_replicas = max(1, min(int(replicas), len(local)))
        self._devices = local[:self.n_replicas]
        #: Host bytes of one params pytree, and the total replicated
        #: device footprint (× n_replicas) — the AOT cache snapshot's
        #: bytes accounting, next to compile_s.
        self.params_bytes_per_replica = int(sum(
            np.asarray(leaf).nbytes
            for leaf in jax.tree.leaves(model.params)))
        self.params_bytes = self.params_bytes_per_replica * self.n_replicas
        self._params_r = [jax.device_put(model.params, d)
                          for d in self._devices]
        self._device = self._devices[0]
        self._params = self._params_r[0]
        # Donation rewrites the batch buffer in place on backends that
        # support it; the CPU test rig would only log a warning per call.
        donate = (1,) if jax.default_backend() in ("tpu", "gpu") else ()
        fn = model.predict_proba_fn

        def rowwise(p, x):
            # One dispatch per BATCH, but rows evaluate one at a time
            # inside the program (on-device lax.map over (1, d) slices).
            # This is deliberate: XLA's batched reductions round
            # shape-dependently (measured on CPU: rf diverges between a
            # (3,d) and a padded (8,d) batch, mlp between (1,d) and
            # (3,d)), so a batched matmul would make a row's probability
            # depend on which bucket its batch coalesced into. Row-wise
            # evaluation pins the per-row compute shape to (1, d) —
            # bit-identical across every bucket AND to the batch
            # predict path's per-row oracle — and micro-batches this
            # size are dispatch-overhead-bound, not FLOP-bound, so the
            # batching win (one dispatch, measured 30-77x over per-row
            # dispatch) is untouched.
            return jax.lax.map(lambda r: fn(p, r[None, :])[0], x)

        jitted = jax.jit(rowwise, donate_argnums=donate)
        x_specs = {
            b: jax.ShapeDtypeStruct((b, self.n_features), jnp.float32)
            for b in self.buckets}
        # The whole bucket-ladder set is a compile site the resource
        # plane accounts: wall time of the build (all replicas) plus the
        # XLA backend-compile seconds the monitoring listener attributes
        # to this window (lo_compile_* on /metrics;
        # docs/observability.md). Lowering against each replica's
        # committed params pins each ladder to that replica's device —
        # explicit placement, so dispatchers on different replicas never
        # contend for one device.
        resources.ensure_listener()
        c0 = resources.compile_seconds()
        t0 = time.monotonic()
        self._programs_r = [
            {b: jitted.lower(params, x_specs[b]).compile()
             for b in self.buckets}
            for params in self._params_r]
        self._programs = self._programs_r[0]
        #: Wall seconds this model's ladder(s) took to build, and the
        #: XLA backend-compile share of it — surfaced per load on the
        #: AOT cache snapshot so a hot-swap's recompile cost is
        #: attributable.
        self.compile_wall_s = round(time.monotonic() - t0, 6)
        self.compile_s = round(resources.compile_seconds() - c0, 6)

    def predict_padded(self, X: np.ndarray, replica: int = 0) -> np.ndarray:
        """One device dispatch for a host batch of ≤ max-bucket rows:
        pad → compiled executable → host probs sliced to the true count.
        This is the ONLY device entry of the online tier; replica
        ``replica``'s dispatcher thread owns that replica's device
        (replica 0 — the default — is the single-device topology)."""
        import jax

        n = len(X)
        bucket = bucket_for(n, self.buckets)
        if n < bucket:
            X = np.concatenate(
                [X, np.zeros((bucket - n, self.n_features), np.float32)],
                axis=0)
        x_dev = jax.device_put(np.ascontiguousarray(X, np.float32),
                               self._devices[replica])
        return np.asarray(self._programs_r[replica][bucket](
            self._params_r[replica], x_dev))[:n]

    def predict(self, X: np.ndarray, replica: int = 0) -> np.ndarray:
        """Probabilities for any host batch on the given replica's
        device; rows beyond the largest bucket run as successive
        max-bucket dispatches. Bit-identical across replicas: the
        row-wise program pins per-row numerics to a (1, d) compute
        shape, and every replica compiles the identical program from
        the identical params bytes."""
        max_b = self.buckets[-1]
        if len(X) <= max_b:
            return self.predict_padded(X, replica)
        return np.concatenate(
            [self.predict_padded(X[i:i + max_b], replica)
             for i in range(0, len(X), max_b)], axis=0)


class AotCache:
    """Persistent in-process cache of compiled predict programs, keyed
    (model name, version, bucket) — version is the manifest file's
    (mtime_ns, size), so a re-save under the same name recompiles and a
    delete raises ``ModelNotFound`` on the next lookup."""

    def __init__(self, registry: ModelRegistry,
                 cfg: Optional[Settings] = None):
        self.registry = registry
        self.cfg = cfg or global_settings
        self.buckets = predict_buckets(self.cfg.serve_max_batch)
        #: Device replicas every entry's ladder is compiled for —
        #: resolved ONCE so every model in this cache has the same
        #: replica topology (the router and the dispatcher set in
        #: serving/batcher.py are sized off the same number).
        self.replicas = resolve_replicas(self.cfg)
        self._lock = threading.Lock()
        self._models: Dict[str, AotModel] = {}
        self._name_locks: Dict[str, threading.Lock] = {}
        #: Per-name swap epoch: bumped each time a (re)built entry is
        #: inserted, stamped onto the entry. Because ONE AotModel holds
        #: ALL replicas' params+ladders and the name maps to exactly one
        #: entry, every replica of a model always serves the same
        #: version — the epoch is the observable token proving which
        #: swap generation a response came from.
        self._epochs: Dict[str, int] = {}
        self._compiles = 0
        self._evictions = 0
        self._hits = 0
        self._compile_s = 0.0

    def entry(self, name: str) -> AotModel:
        """The loaded+compiled model, (re)built when absent or stale.
        The manifest stat per lookup (``ModelRegistry.version``) is the
        staleness probe — ~µs, paid once per request, and what lets a
        hot-swapped model serve its new version without a restart.

        Loading + compiling runs under a PER-NAME lock, never the
        global one: a cold load or hot-swap of one model (seconds of
        XLA compiles for the whole bucket ladder) must not
        head-of-line-block every other model's handlers and
        dispatchers."""
        version = self.registry.version(name)
        with self._lock:
            ent = self._models.get(name)
            if ent is not None and ent.version == version:
                self._hits += 1
                hit = True
            else:
                hit = False
                name_lock = self._name_locks.setdefault(
                    name, threading.Lock())
        if hit:
            # Counted outside the cache lock: a compile-cache hit per
            # served request is the hit leg of lo_compile_* — the miss
            # leg (real backend compiles) comes from the monitoring
            # listener (utils/resources.py).
            resources.note_cache_hit()
            return ent
        with name_lock:
            # Re-read the token under the name lock: a save() completing
            # while we waited means load() below returns the NEW content
            # — tagging it with the pre-wait token would force a full
            # redundant bucket-ladder recompile on the next request.
            version = self.registry.version(name)
            with self._lock:                 # another thread built it?
                ent = self._models.get(name)
                if ent is not None and ent.version == version:
                    return ent
                stale = ent is not None
            # Double-read the token AROUND the load and retry until it
            # is stable: version() is lock-free while load() waits out
            # any in-flight save() on the registry lock, so a lone
            # pre-load read can pair a pre-save token with post-save
            # content — mistagged cache ⇒ the next request's probe
            # mismatches and re-pays the whole seconds-long bucket
            # ladder. Tokens are strictly increasing across saves (no
            # ABA), so token-before == token-after proves the loaded
            # snapshot corresponds to that token; a retry costs one
            # checkpoint restore, never a compile.
            failpoints.fire(FP_PRE_COMPILE)
            while True:
                manifest, model = self.registry.load(name)
                after = self.registry.version(name)
                if after == version:
                    break
                version = after
            ent = AotModel(name, version, manifest, model, self.buckets,
                           replicas=self.replicas)
            # Deleted while we compiled? Re-probe before caching: the
            # bucket-ladder compile takes seconds, and inserting after a
            # DELETE's invalidate() would pin device params for a model
            # that can never serve (and overstate models_loaded) until
            # restart. ModelNotFound propagates as the request's 404.
            # The residual insert-vs-invalidate window is µs, vs the
            # seconds-long window this closes.
            self.registry.version(name)
            with self._lock:
                if stale:
                    self._evictions += 1
                # Stamp the swap epoch under the same lock that makes
                # the entry visible: readers that observe the new entry
                # observe its (strictly increasing) epoch atomically, so
                # no two responses from one epoch can span a version
                # swap.
                ent.swap_epoch = self._epochs.get(name, 0) + 1
                self._epochs[name] = ent.swap_epoch
                self._models[name] = ent
                self._compiles += len(self.buckets) * ent.n_replicas
                self._compile_s = round(
                    self._compile_s + ent.compile_s, 6)
            return ent

    def invalidate(self, name: Optional[str] = None) -> None:
        with self._lock:
            if name is None:
                self._evictions += len(self._models)
                self._models.clear()
            elif self._models.pop(name, None) is not None:
                self._evictions += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"models_loaded": len(self._models),
                    "programs_compiled": self._compiles,
                    "compile_s": round(self._compile_s, 6),
                    "hits": self._hits,
                    "evictions": self._evictions,
                    "buckets": list(self.buckets),
                    "replicas": self.replicas,
                    # Replicated-params device footprint of everything
                    # currently loaded — the bytes side of the
                    # compile_s accounting (ISSUE 16 satellite).
                    "params_bytes": sum(
                        m.params_bytes for m in self._models.values()),
                    # Completed hot-swaps: epoch 1 is the cold load, so
                    # each name contributes (epoch - 1) swaps.
                    "swaps": sum(
                        e - 1 for e in self._epochs.values())}
