"""learningorchestra_tpu — a TPU-native data-science pipeline framework.

A ground-up JAX/XLA re-design of the capabilities of
``StephanieGreenberg/learningOrchestra`` (reference mounted at
``/root/reference``): a named-dataset catalog with CSV-URL ingestion and
lineage metadata, column projection, field-type coercion, histograms, PCA and
t-SNE visualization, and a model builder fitting five classifier families
(lr/dt/rf/gb/nb) concurrently — exposed over REST with a Python client SDK.

Where the reference dispatches compute to an Apache Spark JVM cluster and
stores everything in MongoDB (reference docker-compose.yml:27-163), this
framework keeps datasets as columnar shards in host RAM (with disk
persistence) and runs all compute as jit-compiled JAX programs sharded over a
``jax.sharding.Mesh`` — XLA collectives over ICI/DCN replace Spark shuffles.
"""

__version__ = "0.1.0"

from learningorchestra_tpu.config import Settings, settings  # noqa: F401
