"""lolint engine: file walking, suppression validation, the justified
baseline, and the run entry point the CLI and tests share.

Silencing policy (docs/static_analysis.md §policy):

- inline ``# lolint: disable=<rule>`` — for deliberate one-offs, visible
  in review next to the code it excuses; unknown rule names in a
  directive are themselves findings (rule ``lolint-directive``).
- the baseline file — for grandfathered findings, keyed
  ``(rule, path, symbol)``. Every entry needs a non-empty
  ``justification``; an entry that matches no current finding is STALE
  and fails the run, so the baseline can only shrink honestly (fixing a
  violation forces deleting its excuse).
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.lolint.core import Finding, ParsedFile, Project
from tools.lolint.rules import ALL_RULES, Rule, rule_names

#: Meta-rules emitted by the engine itself (never suppressible).
DIRECTIVE_RULE = "lolint-directive"
BASELINE_RULE = "lolint-baseline"
PARSE_RULE = "lolint-parse"

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")
#: What a bare ``python -m tools.lolint`` scans.
DEFAULT_ROOTS = ("learningorchestra_tpu",)


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    files_scanned: int = 0
    baseline_used: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    def to_doc(self) -> Dict[str, object]:
        return {"ok": self.ok,
                "files_scanned": self.files_scanned,
                "baseline_entries_used": self.baseline_used,
                "counts": self.counts(),
                "findings": [f.to_doc() for f in self.findings]}


def _iter_py_files(roots: Sequence[str], repo_root: str) -> List[str]:
    out: List[str] = []
    for root in roots:
        top = root if os.path.isabs(root) else os.path.join(repo_root, root)
        if os.path.isfile(top):
            out.append(top)
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def _relpath(path: str, repo_root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), repo_root)
    return rel.replace(os.sep, "/")


def load_baseline(path: str) -> Tuple[List[dict], List[Finding]]:
    """Baseline entries + findings for malformed ones (missing
    justification, unknown rule, bad shape)."""
    if not os.path.isfile(path):
        return [], []
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    entries, problems = [], []
    known = set(rule_names())
    bl_rel = _relpath(path, REPO_ROOT)
    for i, ent in enumerate(doc):
        where = f"baseline entry #{i}"
        if not isinstance(ent, dict) or not {
                "rule", "path", "symbol"} <= set(ent):
            problems.append(Finding(
                BASELINE_RULE, bl_rel, 1, 0,
                f"{where} must be an object with rule/path/symbol/"
                "justification keys"))
            continue
        if ent["rule"] not in known:
            problems.append(Finding(
                BASELINE_RULE, bl_rel, 1, 0,
                f"{where} names unknown rule {ent['rule']!r}"))
            continue
        if not str(ent.get("justification", "")).strip():
            problems.append(Finding(
                BASELINE_RULE, bl_rel, 1, 0,
                f"{where} ({ent['rule']} @ {ent['path']}:{ent['symbol']}) "
                "has no justification — every grandfathered finding "
                "carries its written excuse"))
            continue
        entries.append(ent)
    return entries, problems


def run_lint(paths: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[Rule]] = None,
             baseline_path: Optional[str] = DEFAULT_BASELINE,
             repo_root: str = REPO_ROOT) -> LintResult:
    """Lint ``paths`` (repo-relative or absolute; default: the package)
    and fold in suppressions + baseline. This is the single entry point
    the CLI, CI and the test suite all call."""
    rules = list(rules if rules is not None else ALL_RULES)
    known_rules = {r.name for r in rules} | {
        r.name for r in ALL_RULES}
    result = LintResult()
    project = Project(root=repo_root)

    for path in _iter_py_files(paths or DEFAULT_ROOTS, repo_root):
        rel = _relpath(path, repo_root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            pf = ParsedFile(rel, source)
        except (SyntaxError, UnicodeDecodeError) as e:
            result.findings.append(Finding(
                PARSE_RULE, rel, getattr(e, "lineno", 1) or 1, 0,
                f"file does not parse: {e}"))
            continue
        project.files.append(pf)
        result.files_scanned += 1

    raw: List[Finding] = []
    for pf in project.files:
        for rule in rules:
            if rule.applies(pf.path):
                raw.extend(rule.check(pf))
        # A directive naming an unknown rule is an error in its own
        # right: the author believes something is suppressed and it
        # is not (or never will be).
        for line, spec in pf.directives:
            for name in spec.split(","):
                if name not in known_rules:
                    result.findings.append(Finding(
                        DIRECTIVE_RULE, pf.path, line, 0,
                        f"suppression names unknown rule {name!r} "
                        f"(known: {sorted(known_rules)})"))
    for rule in rules:
        raw.extend(rule.finalize(project))

    # Inline suppressions.
    by_path = {pf.path: pf for pf in project.files}
    survivors = []
    for f in raw:
        pf = by_path.get(f.path)
        if pf is not None and pf.suppressed(f):
            continue
        survivors.append(f)

    # Baseline.
    if baseline_path:
        entries, problems = load_baseline(baseline_path)
        result.findings.extend(problems)
        keys = {(e["rule"], e["path"], e["symbol"]): e for e in entries}
        used: Set[Tuple[str, str, str]] = set()
        remaining = []
        for f in survivors:
            if f.baseline_key() in keys:
                used.add(f.baseline_key())
            else:
                remaining.append(f)
        survivors = remaining
        result.baseline_used = len(used)
        bl_rel = _relpath(baseline_path, repo_root)
        # Staleness is only judgeable when this run actually covered the
        # entry: a scoped invocation (a paths subset or --rules subset)
        # simply cannot see findings outside its scope, and flagging
        # those entries stale would make every scoped run fail.
        scanned = {pf.path for pf in project.files}
        active = {r.name for r in rules}
        for key, ent in keys.items():
            if key in used or key[1] not in scanned or key[0] not in active:
                continue
            result.findings.append(Finding(
                BASELINE_RULE, bl_rel, 1, 0,
                f"stale baseline entry {key[0]} @ {key[1]}:"
                f"{key[2]} matches no current finding — delete it "
                "(the violation it excused is gone)"))

    result.findings.extend(survivors)
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result
