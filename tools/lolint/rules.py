"""The lolint rules — this repo's hard-won invariants as AST checks.

Each rule is a class with a ``name``, an ``applies(relpath)`` scope, a
per-file ``check(pf)`` and an optional whole-tree ``finalize(project)``.
docs/static_analysis.md carries the rule table with the PR-6/7 review
finding that motivated each one; keep the two in sync when adding rules.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from tools.lolint.core import (
    Finding, ParsedFile, Project, call_name, dotted_name, iter_body_calls)

PACKAGE = "learningorchestra_tpu"


def _in(relpath: str, *prefixes: str) -> bool:
    return relpath.startswith(prefixes)


class Rule:
    name = ""
    description = ""

    def applies(self, relpath: str) -> bool:
        raise NotImplementedError

    def check(self, pf: ParsedFile) -> Iterator[Finding]:
        return iter(())

    def finalize(self, project: Project) -> Iterator[Finding]:
        return iter(())


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------

#: Callables that put a function under JAX tracing: code inside runs at
#: TRACE time (once, host-side) not at execution time — host effects
#: silently freeze into the program or desync SPMD processes.
_JIT_WRAPPERS = {"jit", "pjit", "shard_map", "pallas_call"}

#: Host-effect calls that must not appear inside traced code.
_IMPURE_CALL_PREFIXES = (
    "np.random.", "numpy.random.", "random.", "time.", "requests.",
)
_IMPURE_CALLS = {"print", "open", "os.getenv", "os.urandom", "input"}
#: Method names that force a host sync / host value inside a trace.
_IMPURE_ATTR_CALLS = {"item", "block_until_ready", "tolist"}


def _jit_wrapper_target(call: ast.Call) -> Optional[ast.AST]:
    """For ``jax.jit(fn, ...)`` / ``partial(jax.jit, fn)`` /
    ``pl.pallas_call(kernel, ...)``, the wrapped function expression."""
    name = call_name(call)
    last = name.rsplit(".", 1)[-1]
    if last in _JIT_WRAPPERS and call.args:
        return call.args[0]
    if last == "partial" and call.args:
        inner = call.args[0]
        if (isinstance(inner, (ast.Name, ast.Attribute)) and
                dotted_name(inner).rsplit(".", 1)[-1] in _JIT_WRAPPERS):
            return call.args[1] if len(call.args) > 1 else None
    return None


class JitPurityRule(Rule):
    name = "jit-purity"
    description = ("no print/np.random/time/os.environ/.item()/global "
                   "mutation inside jit/pjit/shard_map/Pallas-traced "
                   "functions")

    def applies(self, relpath: str) -> bool:
        return _in(relpath, PACKAGE)

    def _traced_functions(self, pf: ParsedFile) -> List[ast.AST]:
        by_name: Dict[str, List[ast.AST]] = {}
        for fn in pf.functions():
            by_name.setdefault(fn.name, []).append(fn)
        traced: Dict[int, ast.AST] = {}

        def mark(node: Optional[ast.AST]) -> None:
            if node is None:
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                traced[id(node)] = node
            elif isinstance(node, ast.Name):
                for fn in by_name.get(node.id, ()):
                    traced[id(fn)] = fn

        for fn in pf.functions():
            for deco in fn.decorator_list:
                dname = dotted_name(deco).rsplit(".", 1)[-1]
                if dname in _JIT_WRAPPERS:
                    traced[id(fn)] = fn
                elif isinstance(deco, ast.Call):
                    last = call_name(deco).rsplit(".", 1)[-1]
                    if last in _JIT_WRAPPERS:
                        traced[id(fn)] = fn
                    elif last == "partial" and deco.args:
                        inner = dotted_name(deco.args[0]).rsplit(".", 1)[-1]
                        if inner in _JIT_WRAPPERS:
                            traced[id(fn)] = fn
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Call):
                mark(_jit_wrapper_target(node))
        return list(traced.values())

    def check(self, pf: ParsedFile) -> Iterator[Finding]:
        for fn in self._traced_functions(pf):
            sym = pf.symbol_of(fn) or getattr(fn, "name", "<lambda>")
            # The whole lexical subtree is traced — nested defs/lambdas
            # inside a jitted function execute under the same trace.
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    yield Finding(
                        self.name, pf.path, node.lineno, node.col_offset,
                        "global-statement inside a traced function: "
                        "mutation happens at trace time, not per call",
                        sym)
                if isinstance(node, ast.Attribute) and \
                        dotted_name(node) == "os.environ":
                    yield Finding(
                        self.name, pf.path, node.lineno, node.col_offset,
                        "os.environ read inside a traced function freezes "
                        "the env value into the compiled program", sym)
                if not isinstance(node, ast.Call):
                    continue
                cname = call_name(node)
                short = cname.rsplit(".", 1)[-1]
                if cname in _IMPURE_CALLS or any(
                        cname.startswith(p) for p in _IMPURE_CALL_PREFIXES):
                    # jax.random / jax.numpy are fine; host RNG/clock/IO
                    # is what desyncs traces.
                    yield Finding(
                        self.name, pf.path, node.lineno, node.col_offset,
                        f"host-effect call {cname}() inside a traced "
                        "function (runs at trace time / desyncs SPMD "
                        "processes)", sym)
                elif (isinstance(node.func, ast.Attribute)
                      and short in _IMPURE_ATTR_CALLS
                      and not cname.startswith(("np.", "numpy."))):
                    yield Finding(
                        self.name, pf.path, node.lineno, node.col_offset,
                        f".{short}() inside a traced function forces a "
                        "host sync mid-trace", sym)


# ---------------------------------------------------------------------------
# lock-blocking
# ---------------------------------------------------------------------------

#: Held-lock context expressions are recognized by name: the repo's
#: locks are uniformly *_lock / _cond / name_lock (threading.Lock /
#: Condition attributes).
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|cond|mutex)$", re.IGNORECASE)

_BLOCKING_PREFIXES = ("requests.", "shutil.", "subprocess.", "socket.",
                      "urllib.")
_BLOCKING_EXACT = {"time.sleep", "os.replace", "os.rename", "os.fsync",
                   "os.remove", "os.makedirs", "json.dump", "json.load"}
#: Method names that dispatch device work, do I/O, or block regardless
#: of receiver. ``join`` is special-cased to thread-ish receivers so
#: ``",".join(...)`` stays clean.
_BLOCKING_ATTRS = {"block_until_ready", "device_put", "lower", "compile",
                   "restore", "save", "load", "result", "serve_forever",
                   "sleep"}


class LockBlockingRule(Rule):
    name = "lock-blocking"
    description = ("no device dispatch, file/network I/O, orbax "
                   "save/load, sleep or thread joins while holding a "
                   "lock on the serving/catalog hot paths")

    SCOPE = (
        f"{PACKAGE}/serving/",
        f"{PACKAGE}/catalog/readpipe.py",
        f"{PACKAGE}/models/aot.py",
        f"{PACKAGE}/models/registry.py",
        f"{PACKAGE}/models/persistence.py",
    )

    def applies(self, relpath: str) -> bool:
        return _in(relpath, *self.SCOPE)

    @staticmethod
    def _held_lock(item: ast.withitem) -> Optional[str]:
        name = dotted_name(item.context_expr)
        if not name:
            return None
        last = name.rsplit(".", 1)[-1]
        return name if _LOCK_NAME_RE.search(last) else None

    def check(self, pf: ParsedFile) -> Iterator[Finding]:
        for node in ast.walk(pf.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            locks = [self._held_lock(i) for i in node.items]
            locks = [x for x in locks if x]
            if not locks:
                continue
            sym = pf.symbol_of(node)
            for stmt in node.body:
                # Nested defs are skipped: they run later, lock-free.
                for call in iter_body_calls(stmt):
                    yield from self._check_call(pf, call, locks[0], sym)

    def _check_call(self, pf: ParsedFile, call: ast.Call, lock: str,
                    sym: str) -> Iterator[Finding]:
        cname = call_name(call)
        short = cname.rsplit(".", 1)[-1]
        receiver = cname.rsplit(".", 1)[0] if "." in cname else ""
        blocking = None
        if cname == "open" or cname in _BLOCKING_EXACT or any(
                cname.startswith(p) for p in _BLOCKING_PREFIXES):
            blocking = f"{cname}()"
        elif isinstance(call.func, ast.Attribute):
            if short in _BLOCKING_ATTRS:
                # cond.wait() RELEASES the lock — that is the whole
                # point of a condition variable; never flag it. (wait
                # is not in the set, this comment documents why.)
                blocking = f".{short}()"
            elif short == "join" and re.search(
                    r"thread|proc|worker|pool", receiver, re.IGNORECASE):
                blocking = f".{short}()"
        if blocking:
            yield Finding(
                self.name, pf.path, call.lineno, call.col_offset,
                f"{blocking} while holding {lock}: blocking work under a "
                "hot lock head-of-line-stalls every other thread on it "
                "(the PR 6 registry-version stall class)", sym)


# ---------------------------------------------------------------------------
# env-discipline
# ---------------------------------------------------------------------------

class EnvDisciplineRule(Rule):
    name = "env-discipline"
    description = ("every LO_TPU_* env read goes through config.py, and "
                   "every knob config.py names appears in docs/")

    CONFIG = f"{PACKAGE}/config.py"

    def applies(self, relpath: str) -> bool:
        return _in(relpath, PACKAGE) and relpath != self.CONFIG

    @staticmethod
    def _env_key(pf: ParsedFile, node: ast.AST) -> Optional[str]:
        """The env-var key of an os.environ/os.getenv access, resolving
        module-level string constants; None when not an env read or the
        key is dynamic."""
        key_expr: Optional[ast.AST] = None
        if isinstance(node, ast.Call):
            cname = call_name(node)
            if cname == "os.getenv" and node.args:
                key_expr = node.args[0]
            elif cname in ("os.environ.get", "environ.get") and node.args:
                key_expr = node.args[0]
        elif isinstance(node, ast.Subscript) and dotted_name(
                node.value) in ("os.environ", "environ"):
            key_expr = node.slice
        elif isinstance(node, ast.Compare) and len(node.comparators) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and dotted_name(node.comparators[0]) in ("os.environ",
                                                         "environ"):
            key_expr = node.left
        if key_expr is None:
            return None
        if isinstance(key_expr, ast.Constant) and isinstance(
                key_expr.value, str):
            return key_expr.value
        if isinstance(key_expr, ast.Name):
            return pf.str_constants.get(key_expr.id)
        return None

    def check(self, pf: ParsedFile) -> Iterator[Finding]:
        for node in ast.walk(pf.tree):
            key = self._env_key(pf, node)
            if key and key.startswith("LO_TPU_"):
                yield Finding(
                    self.name, pf.path, node.lineno, node.col_offset,
                    f"direct read of {key}: LO_TPU_* knobs go through "
                    "config.py (Settings field or accessor) so every knob "
                    "is typed, discoverable and documented in one place",
                    pf.symbol_of(node))

    def finalize(self, project: Project) -> Iterator[Finding]:
        cfg = project.by_path(self.CONFIG)
        if cfg is None:
            return
        docs = project.docs_text()
        seen: Set[str] = set()
        for m in re.finditer(r"LO_TPU_[A-Z0-9_]+", cfg.source):
            knob = m.group(0)
            if knob in seen:
                continue
            seen.add(knob)
            if knob not in docs:
                line = cfg.source[:m.start()].count("\n") + 1
                yield Finding(
                    self.name, cfg.path, line, 0,
                    f"knob {knob} is defined in config.py but documented "
                    "nowhere under docs/ (add it to "
                    "docs/configuration.md)", "")


# ---------------------------------------------------------------------------
# thread-lifecycle
# ---------------------------------------------------------------------------

class ThreadLifecycleRule(Rule):
    name = "thread-lifecycle"
    description = ("every threading.Thread start site is named and "
                   "carries a '# thread-lifecycle:' ownership/join/"
                   "excepthook annotation")

    def applies(self, relpath: str) -> bool:
        return _in(relpath, PACKAGE)

    def check(self, pf: ParsedFile) -> Iterator[Finding]:
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            if cname.rsplit(".", 1)[-1] != "Thread" or \
                    not cname.endswith(("threading.Thread", "Thread")):
                continue
            sym = pf.symbol_of(node)
            kwargs = {kw.arg for kw in node.keywords}
            if "name" not in kwargs:
                yield Finding(
                    self.name, pf.path, node.lineno, node.col_offset,
                    "threading.Thread() without name=: an unnamed thread's "
                    "death is unattributable in excepthook reports and "
                    "stack dumps", sym)
            if "thread-lifecycle:" not in pf.comment_near(node.lineno):
                yield Finding(
                    self.name, pf.path, node.lineno, node.col_offset,
                    "thread start site lacks a '# thread-lifecycle: "
                    "owner=<component> exit=<join/daemon/excepthook "
                    "story>' annotation — the PR 6 dispatcher died "
                    "silently precisely because nobody owned its exit "
                    "path", sym)


# ---------------------------------------------------------------------------
# handler-error-map
# ---------------------------------------------------------------------------

class HandlerErrorMapRule(Rule):
    name = "handler-error-map"
    description = ("serving code: no bare except, no silent exception "
                   "swallowing, and every serving-defined exception "
                   "class is mapped to a status code in some except "
                   "clause")

    SCOPE = (f"{PACKAGE}/serving/",)

    def applies(self, relpath: str) -> bool:
        return _in(relpath, *self.SCOPE)

    def check(self, pf: ParsedFile) -> Iterator[Finding]:
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            sym = pf.symbol_of(node)
            if node.type is None:
                yield Finding(
                    self.name, pf.path, node.lineno, node.col_offset,
                    "bare 'except:' catches SystemExit/KeyboardInterrupt "
                    "and turns any bug into silence — name the exception "
                    "classes and map them to status codes", sym)
                continue
            broad = dotted_name(node.type).rsplit(".", 1)[-1] in (
                "Exception", "BaseException")
            swallows = all(isinstance(s, ast.Pass) for s in node.body)
            if broad and swallows:
                yield Finding(
                    self.name, pf.path, node.lineno, node.col_offset,
                    "'except Exception: pass' black-holes failures (the "
                    "PR 6 silent-dispatcher-death class): re-raise, map "
                    "to an HttpError, or at minimum log it", sym)

    @staticmethod
    def _exception_classes(pf: ParsedFile) -> Iterator[ast.ClassDef]:
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            bases = {dotted_name(b).rsplit(".", 1)[-1] for b in node.bases}
            if bases & {"Exception", "RuntimeError", "ValueError",
                        "KeyError", "OSError", "TimeoutError"} or \
                    any(b.endswith("Error") for b in bases):
                yield node

    def finalize(self, project: Project) -> Iterator[Finding]:
        serving = [pf for pf in project.files
                   if self.applies(pf.path)]
        handled: Set[str] = set()
        for pf in serving:
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.ExceptHandler) and node.type is not None:
                    types = (node.type.elts
                             if isinstance(node.type, ast.Tuple)
                             else [node.type])
                    for t in types:
                        handled.add(dotted_name(t).rsplit(".", 1)[-1])
        for pf in serving:
            for cls in self._exception_classes(pf):
                if cls.name not in handled:
                    yield Finding(
                        self.name, pf.path, cls.lineno, cls.col_offset,
                        f"exception class {cls.name} is defined in "
                        "serving/ but no serving except clause maps it — "
                        "an unmapped raise surfaces as a raw 500 (the "
                        "PR 6 BatcherStopped hole)", cls.name)


# ---------------------------------------------------------------------------
# log-discipline
# ---------------------------------------------------------------------------

#: Module-level ``logging.X(...)`` calls that go through the ROOT logger
#: (or mutate global logging config) instead of a named ``lo_tpu.*``
#: logger — lines emitted that way carry no component name and bypass
#: the structured formatter's trace-id stamping entirely.
_ROOT_LOGGER_CALLS = {"debug", "info", "warning", "warn", "error",
                      "exception", "critical", "fatal", "log",
                      "basicConfig"}


class LogDisciplineRule(Rule):
    name = "log-discipline"
    description = ("package code logs through utils/structlog "
                   "(named lo_tpu.* loggers): no bare print(), no "
                   "root-logger logging.* calls or basicConfig")

    #: structlog itself legitimately owns the handler/formatter wiring.
    EXEMPT = (f"{PACKAGE}/utils/structlog.py",)

    def applies(self, relpath: str) -> bool:
        return _in(relpath, PACKAGE) and relpath not in self.EXEMPT

    def check(self, pf: ParsedFile) -> Iterator[Finding]:
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            if cname == "print":
                yield Finding(
                    self.name, pf.path, node.lineno, node.col_offset,
                    "bare print() in package code: unleveled, "
                    "unfilterable, and invisible to the structured "
                    "logger's trace-id stamping — use "
                    "structlog.get_logger(...)", pf.symbol_of(node))
            elif cname.startswith("logging.") and \
                    cname.rsplit(".", 1)[-1] in _ROOT_LOGGER_CALLS:
                yield Finding(
                    self.name, pf.path, node.lineno, node.col_offset,
                    f"{cname}() goes through the ROOT logger (or mutates "
                    "global logging config): package code logs through a "
                    "named structlog.get_logger(...) logger so every "
                    "line carries its component and trace ids",
                    pf.symbol_of(node))
            elif cname in ("logging.getLogger", "getLogger"):
                # Any getLogger whose name literal is not under the
                # lo_tpu tree mints a logger the structured handler
                # never sees — whether used chained, assigned to a
                # module `log`, or passed around. __name__ yields
                # `learningorchestra_tpu.*`, which is exactly the
                # pre-PR-9 bypass.
                arg = node.args[0] if node.args else None
                under_tree = (isinstance(arg, ast.Constant)
                              and isinstance(arg.value, str)
                              and (arg.value == "lo_tpu"
                                   or arg.value.startswith("lo_tpu.")))
                if not under_tree:
                    yield Finding(
                        self.name, pf.path, node.lineno, node.col_offset,
                        f"{cname}() with a name outside the lo_tpu tree "
                        "(dynamic, __name__, or bare): lines emitted "
                        "through it bypass the structured handler — no "
                        "level policy, no trace/span ids; use "
                        "structlog.get_logger(<component>)",
                        pf.symbol_of(node))


# ---------------------------------------------------------------------------
# metric-doc-coverage
# ---------------------------------------------------------------------------

class MetricDocCoverageRule(Rule):
    name = "metric-doc-coverage"
    description = ("every lo_* Prometheus series name emitted by "
                   "utils/prometheus.py appears in "
                   "docs/observability.md")

    PROMETHEUS = f"{PACKAGE}/utils/prometheus.py"
    DOC = "docs/observability.md"

    def applies(self, relpath: str) -> bool:
        return relpath == self.PROMETHEUS

    @classmethod
    def series_names(cls, pf: ParsedFile) -> Dict[str, int]:
        """Every statically resolvable ``lo_*`` series name (or, when
        an f-string's placeholder cannot be resolved, its literal
        prefix) emitted by the exposition renderer, mapped to a source
        line. f-string placeholders resolve against the NEAREST
        enclosing ``for <name> in (<string literals>)`` loop — the
        renderer's per-key loops — so ``f"lo_serving_{key}_total"``
        expands to the exact series it emits, never a cross-loop
        cartesian superset."""
        names: Dict[str, int] = {}

        def visit(node: ast.AST, env: Dict[str, List[str]]) -> None:
            if isinstance(node, ast.For) and isinstance(
                    node.target, ast.Name) and isinstance(
                    node.iter, (ast.Tuple, ast.List)) and node.iter.elts \
                    and all(isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                            for e in node.iter.elts):
                inner = dict(env)
                inner[node.target.id] = [e.value for e in node.iter.elts]
                for child in node.body + node.orelse:
                    visit(child, inner)
                return
            if isinstance(node, ast.Constant) and isinstance(
                    node.value, str) and node.value.startswith("lo_"):
                names.setdefault(node.value, node.lineno)
            elif isinstance(node, ast.JoinedStr):
                cls._expand_fstring(node, env, names)
            for child in ast.iter_child_nodes(node):
                visit(child, env)

        visit(pf.tree, {})
        return names

    @staticmethod
    def _expand_fstring(node: ast.JoinedStr, env: Dict[str, List[str]],
                        names: Dict[str, int]) -> None:
        parts = node.values
        if not (parts and isinstance(parts[0], ast.Constant)
                and str(parts[0].value).startswith("lo_")):
            return
        expansions = [""]
        for p in parts:
            if isinstance(p, ast.Constant):
                expansions = [e + str(p.value) for e in expansions]
            elif isinstance(p, ast.FormattedValue) and isinstance(
                    p.value, ast.Name) and p.value.id in env:
                expansions = [e + v for e in expansions
                              for v in env[p.value.id]]
            else:
                # Unresolvable placeholder: fall back to the literal
                # prefix — the doc then needs at least a lo_<prefix>_*
                # mention (substring match).
                names.setdefault(str(parts[0].value), node.lineno)
                return
        for name in expansions:
            names.setdefault(name, node.lineno)

    def finalize(self, project: Project) -> Iterator[Finding]:
        pf = project.by_path(self.PROMETHEUS)
        if pf is None:
            return
        doc_path = os.path.join(project.root, self.DOC)
        try:
            with open(doc_path, encoding="utf-8") as f:
                doc_text = f.read()
        except OSError:
            doc_text = ""
        for name, line in sorted(self.series_names(pf).items()):
            if name not in doc_text:
                yield Finding(
                    self.name, pf.path, line, 0,
                    f"series {name} is emitted on /metrics?format="
                    "prometheus but documented nowhere in "
                    f"{self.DOC} — an operator grepping the docs for a "
                    "dashboard series must find every name the "
                    "exposition can produce", "")


# ---------------------------------------------------------------------------
# failpoint-coverage
# ---------------------------------------------------------------------------

class FailpointCoverageRule(Rule):
    name = "failpoint-coverage"
    description = ("catalog/ rename/fsync two-phase commits AND serving/ "
                   "device-dispatch / response-write sites carry a "
                   "registered failpoints.fire site; fire() sites use "
                   "declared constants")

    SCOPE = (f"{PACKAGE}/catalog/", f"{PACKAGE}/serving/")
    _COMMIT_CALLS = ("os.rename", "os.replace", "os.fsync")
    #: serving/ trigger suffixes: the device dispatch the batcher's
    #: coalescing loop makes (``entry.predict(...)`` — an AOT entry
    #: bound locally, so the dotted name is stable), the HTTP
    #: response-write boundary (``self.wfile.write``), and the
    #: multi-worker front end's request-relay seam — a worker queuing a
    #: frame onto the row channel (``chan.queue_frame``), where the
    #: pre_forward/pre_reply chaos pair must be able to crash/stall a
    #: request mid-hop (tests/test_frontend.py). All are the exact
    #: seams the serving chaos tests (wedged dispatcher, deadline
    #: expiry, committed-but-unsent response, worker death mid-request)
    #: must be able to reach.
    _SERVING_TRIGGER_SUFFIXES = ("entry.predict", "wfile.write",
                                 "chan.queue_frame")
    #: catalog/replicate.py trigger suffix: every socket send seam of
    #: the replication plane (``sock.sendall`` / ``conn.sendall``) —
    #: the exact hops the peer-loss chaos sweep must be able to crash,
    #: tear or stall mid-push / mid-fetch / mid-reply. fsync/rename
    #: commit seams are already covered file-wide by _COMMIT_CALLS.
    _REPLICATE_TRIGGER_SUFFIXES = ("sendall",)
    REPLICATE_PATH = f"{PACKAGE}/catalog/replicate.py"

    def applies(self, relpath: str) -> bool:
        return _in(relpath, *self.SCOPE)

    def check(self, pf: ParsedFile) -> Iterator[Finding]:
        serving = pf.path.startswith(f"{PACKAGE}/serving/")
        replication = pf.path == self.REPLICATE_PATH
        declared = self.declared_sites(pf)
        seen: Set[int] = set()
        for fn in pf.functions():
            if id(fn) in seen:
                continue
            commits: List[ast.Call] = []
            fires: List[ast.Call] = []
            # Whole lexical subtree: a fire() inside a nested helper
            # (store._mirror's copy_files) still covers its enclosing
            # commit function, and nested defs are not re-visited as
            # standalone functions.
            for inner in ast.walk(fn):
                if isinstance(inner,
                              (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and inner is not fn:
                    seen.add(id(inner))
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                cname = call_name(node)
                if cname in self._COMMIT_CALLS or (
                        serving and any(
                            cname == s or cname.endswith("." + s)
                            for s in self._SERVING_TRIGGER_SUFFIXES)) or (
                        replication and any(
                            cname == s or cname.endswith("." + s)
                            for s in self._REPLICATE_TRIGGER_SUFFIXES)):
                    # Attribute-boundary match: `entry.predict` /
                    # `x.entry.predict` trigger, `reentry.predict`
                    # does not.
                    commits.append(node)
                elif cname.rsplit(".", 1)[-1] == "fire" and \
                        "failpoint" in cname:
                    fires.append(node)
            if commits and not fires:
                first = commits[0]
                sym = pf.symbol_of(fn)
                if replication:
                    what = "replication send/commit seam"
                    proof = ("the peer-loss chaos sweep (tests/"
                             "test_failpoints.py replicate.* sites) "
                             "cannot kill/tear this hop mid-push")
                elif serving:
                    what = "device-dispatch/response-write site"
                    proof = ("the serving chaos tests (tests/"
                             "test_serving_fault.py) cannot wedge/crash "
                             "this seam")
                else:
                    what = "commit point"
                    proof = ("the crash sweep (tests/test_failpoints.py) "
                             "cannot prove recovery at this I/O boundary")
                yield Finding(
                    self.name, pf.path, first.lineno, first.col_offset,
                    f"{call_name(first)}() {what} without a "
                    f"failpoints.fire() site in the same function: "
                    f"{proof}", sym)
            for fire in fires:
                if not fire.args:
                    continue
                arg = fire.args[0]
                if isinstance(arg, ast.Constant):
                    yield Finding(
                        self.name, pf.path, fire.lineno, fire.col_offset,
                        "failpoints.fire() with a string literal: pass a "
                        "module-level constant bound via "
                        "failpoints.declare() so the site enters the "
                        "introspectable registry the sweep enumerates",
                        pf.symbol_of(fn))
                elif isinstance(arg, ast.Name) and arg.id not in declared:
                    yield Finding(
                        self.name, pf.path, fire.lineno, fire.col_offset,
                        f"failpoints.fire({arg.id}) but {arg.id} is not "
                        "bound from failpoints.declare() at module level "
                        "in this file — undeclared sites never enter the "
                        "sweep registry", pf.symbol_of(fn))

    @staticmethod
    def declared_sites(pf: ParsedFile) -> Dict[str, str]:
        """Module-level ``CONST = failpoints.declare("site")`` bindings:
        constant name -> site string. Exposed for the runtime
        cross-check test against failpoints.sites()."""
        out: Dict[str, str] = {}
        for stmt in pf.tree.body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)):
                continue
            cname = call_name(stmt.value)
            if cname.rsplit(".", 1)[-1] == "declare" and "failpoint" in \
                    cname and stmt.value.args and isinstance(
                        stmt.value.args[0], ast.Constant):
                out[stmt.targets[0].id] = stmt.value.args[0].value
        return out


ALL_RULES: Tuple[Rule, ...] = (
    JitPurityRule(),
    LockBlockingRule(),
    EnvDisciplineRule(),
    ThreadLifecycleRule(),
    HandlerErrorMapRule(),
    LogDisciplineRule(),
    MetricDocCoverageRule(),
    FailpointCoverageRule(),
)


def rule_names() -> List[str]:
    return [r.name for r in ALL_RULES]


def rules_by_name(names: Optional[Iterable[str]] = None) -> List[Rule]:
    if names is None:
        return list(ALL_RULES)
    wanted = set(names)
    unknown = wanted - set(rule_names())
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)} "
                         f"(known: {rule_names()})")
    return [r for r in ALL_RULES if r.name in wanted]
