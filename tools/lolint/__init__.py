"""lolint — the project-invariant static analyzer.

Six review rounds on PR 6 hand-caught the same defect classes over and
over: blocking work under hot locks, silent dispatcher-thread death,
raw ``TypeError`` → 500 in handlers, and ``LO_TPU_*`` env reads
scattered outside ``config.py``. lolint encodes those hard-won
invariants as machine-checkable AST rules and gates CI on them
(docs/static_analysis.md has the rule table with the review findings
that motivated each one).

Usage::

    python -m tools.lolint [paths...] [--json] [--no-baseline]

Findings can be silenced two ways, both audited:

- inline, for a deliberate one-off: ``# lolint: disable=<rule>`` on the
  offending line (an unknown rule name in the directive is itself an
  error, so typos cannot silently disable nothing);
- the baseline file (``tools/lolint/baseline.json``) for grandfathered
  findings, keyed (rule, file, enclosing symbol) so they survive
  line-number drift — every entry MUST carry a written justification,
  and stale entries (matching nothing) fail the run so the file can
  only shrink honestly.
"""

from tools.lolint.core import Finding, ParsedFile, Project, parse_source
from tools.lolint.engine import run_lint
from tools.lolint.rules import ALL_RULES, rule_names

__all__ = ["Finding", "ParsedFile", "Project", "parse_source",
           "run_lint", "ALL_RULES", "rule_names"]
