"""CLI: ``python -m tools.lolint [paths...]``.

Exit codes: 0 clean (after suppressions + baseline), 1 findings, 2 bad
invocation. ``--json`` emits the machine-readable report CI artifacts
consume; the default text form is one clickable ``path:line:col`` per
finding.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.lolint.engine import (
    DEFAULT_BASELINE, REPO_ROOT, run_lint)
from tools.lolint.rules import ALL_RULES, rule_names, rules_by_name


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lolint",
        description="lolint — this repo's project-invariant static "
                    "analyzer (docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--rules", help="comma-separated subset of rules")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/lolint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.name:20s} {r.description}")
        return 0

    try:
        rules = rules_by_name(
            [s.strip() for s in args.rules.split(",")] if args.rules
            else None)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    result = run_lint(
        paths=args.paths or None, rules=rules,
        baseline_path=None if args.no_baseline else args.baseline,
        repo_root=REPO_ROOT)

    if args.as_json:
        print(json.dumps(result.to_doc(), indent=1))
    else:
        for f in result.findings:
            print(f.render())
        counts = ", ".join(f"{k}={v}" for k, v in result.counts().items())
        print(f"lolint: {len(result.findings)} finding(s) "
              f"[{counts or 'clean'}] across {result.files_scanned} "
              f"file(s); known rules: {', '.join(rule_names())}")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
