"""lolint core: parsed-file model, AST helpers, suppression directives.

Everything here is rule-agnostic plumbing. A :class:`ParsedFile` bundles
one module's AST with the comment/suppression index rules need;
:class:`Project` is the whole-tree view for cross-file checks (doc
coverage, exception-map completeness, failpoint registry cross-checks).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

#: ``# lolint: disable=rule-a,rule-b`` — suppress those rules on this
#: line. ``disable-file=`` widens the suppression to the whole file.
_DIRECTIVE_RE = re.compile(
    r"#\s*lolint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\- ]+)")


@dataclass
class Finding:
    """One rule violation at a source location. ``symbol`` is the
    enclosing function/class qualname — the stable anchor baseline
    entries key on (line numbers drift; symbols rarely do)."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    symbol: str = ""

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def render(self) -> str:
        where = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message}{where}")

    def to_doc(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "symbol": self.symbol}


def dotted_name(node: ast.AST) -> str:
    """``jax.random.normal`` for an Attribute/Name chain; "" when the
    expression is not a plain dotted name (subscripts, calls, …).
    ``a().b`` renders "().b" — callers match on suffix/prefix, so an
    intermediate call degrades to a miss, never a crash."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else f"().{node.attr}"
    if isinstance(node, ast.Call):
        return "()"
    return ""


def call_name(node: ast.Call) -> str:
    return dotted_name(node.func)


def iter_body_calls(node: ast.AST,
                    enter_functions: bool = False) -> Iterator[ast.Call]:
    """Calls lexically inside ``node``'s body. By default nested
    function/lambda definitions are NOT entered — including when
    ``node`` itself is one: code inside them runs when *they* are
    called, not while the enclosing block (e.g. a held lock) executes."""
    if not enter_functions and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return
    for child in ast.iter_child_nodes(node):
        if not enter_functions and isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(child, ast.Call):
            yield child
        yield from iter_body_calls(child, enter_functions)


class ParsedFile:
    """One source file, parsed once, with the indexes every rule needs."""

    def __init__(self, path: str, source: str):
        #: Repo-relative posix path — what findings and baselines carry.
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        #: line number -> full comment text on that line.
        self.comments: Dict[int, str] = {}
        #: line -> set of rule names disabled on that line.
        self.suppressions: Dict[int, Set[str]] = {}
        #: rule names disabled for the whole file.
        self.file_suppressions: Set[str] = set()
        #: (line, text) of every lolint directive — validated by the
        #: engine against the rule registry (a typo'd rule name must be
        #: an error, not a silent no-op).
        self.directives: List[Tuple[int, str]] = []
        self._scan_comments()
        #: node -> enclosing qualname, filled lazily.
        self._qualnames: Dict[int, str] = {}
        self._index_symbols()
        #: module-level NAME = "string constant" assignments (lets rules
        #: resolve e.g. ``os.environ.get(ENV_VAR)``).
        self.str_constants: Dict[str, str] = {}
        for stmt in self.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                self.str_constants[stmt.targets[0].id] = stmt.value.value

    # -- comments / suppressions ---------------------------------------------

    def _scan_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                line = tok.start[0]
                self.comments[line] = tok.string
                m = _DIRECTIVE_RE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group("rules").split(",")
                         if r.strip()}
                self.directives.append((line, ",".join(sorted(rules))))
                if m.group("scope"):
                    self.file_suppressions |= rules
                else:
                    self.suppressions.setdefault(line, set()).update(rules)
        except tokenize.TokenError:
            pass  # ast.parse succeeded; a tokenize hiccup only loses comments

    def comment_near(self, line: int) -> str:
        """Concatenated comment text attached to ``line``: the comment
        on the line itself plus the contiguous run of commented lines
        directly above — where (possibly multi-line) ownership
        annotations live. A blank/uncommented line ends the run, so a
        stray annotation further up never excuses an unrelated site."""
        parts = [self.comments.get(line, "")]
        ln = line - 1
        while ln >= 1 and ln in self.comments:
            parts.append(self.comments[ln])
            ln -= 1
        return " ".join(reversed(parts))

    def suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_suppressions:
            return True
        return finding.rule in self.suppressions.get(finding.line, set())

    # -- symbols -------------------------------------------------------------

    def _index_symbols(self) -> None:
        def visit(node: ast.AST, stack: List[str]) -> None:
            for child in ast.iter_child_nodes(node):
                name = getattr(child, "name", None)
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    child_stack = stack + [name]
                else:
                    child_stack = stack
                self._qualnames[id(child)] = ".".join(child_stack)
                visit(child, child_stack)

        self._qualnames[id(self.tree)] = ""
        visit(self.tree, [])

    def symbol_of(self, node: ast.AST) -> str:
        """Qualname of the symbol *containing* ``node`` ("" = module).
        For a FunctionDef/ClassDef node itself, that includes its own
        name — findings on a def anchor to the def."""
        return self._qualnames.get(id(node), "")

    def functions(self) -> Iterator[ast.AST]:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node


@dataclass
class Project:
    """Whole-tree context handed to rule ``finalize`` hooks."""

    root: str
    files: List[ParsedFile] = field(default_factory=list)

    def by_path(self, path: str) -> Optional[ParsedFile]:
        for pf in self.files:
            if pf.path == path:
                return pf
        return None

    def docs_text(self) -> str:
        """Concatenated markdown under <root>/docs — the doc-coverage
        corpus for env-discipline."""
        chunks = []
        docs = os.path.join(self.root, "docs")
        if os.path.isdir(docs):
            for fn in sorted(os.listdir(docs)):
                if fn.endswith(".md"):
                    with open(os.path.join(docs, fn), encoding="utf-8") as f:
                        chunks.append(f.read())
        return "\n".join(chunks)


def parse_source(source: str, relpath: str) -> ParsedFile:
    """Parse an in-memory source blob under a pretend repo path — how
    the fixture tests run scoped rules on snippets that live outside
    the scoped directories."""
    return ParsedFile(relpath, source)
