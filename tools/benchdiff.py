"""benchdiff — normalize BENCH_*.json schemas and gate on regressions.

The bench trajectory (BENCH_r0N.json, BENCH_serving.json,
MULTICHIP_r0N.json) has grown three shapes over the PRs: driver wrappers
(``{n, cmd, rc, tail, parsed}``), bare metric documents, and lists of
metric documents. Nothing machine-checked it — a perf regression only
surfaced if a human re-read the numbers. This tool:

1. **normalizes** any of those shapes into a flat
   ``{dotted.metric.path: number}`` mapping;
2. **diffs** a candidate run against a baseline run under per-metric
   tolerances, with direction inferred from the metric name (latency /
   wall-clock keys are worse when HIGHER; throughput / speedup keys are
   worse when LOWER; everything else is informational);
3. exits **non-zero on any regression** — the CI perf gate
   (.github/workflows/ci.yml ``bench-smoke``), which also proves the
   gate live against an injected-regression fixture each run.

Usage::

    python -m tools.benchdiff BASELINE.json CANDIDATE.json \
        [--tolerance 'PATTERN=REL'] [--default-tolerance REL] \
        [--require-equal 'PATTERN'] [--json]

``PATTERN`` is an ``fnmatch`` glob over the dotted metric path
(``closed_loop.p99_ms``, ``open_loop.0.p99_ms``, ...). ``REL`` is the
allowed relative worsening (``0.2`` = candidate may be up to 20% worse).
``--require-equal`` pins keys (error/mismatch counters) to exact
equality-or-better regardless of tolerance. Stdlib-only, like every
tools/ gate.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

#: Metric-name suffixes whose value is worse when HIGHER (latency,
#: wall-clock, failure counts).
HIGHER_IS_WORSE = ("p50_ms", "p99_ms", "wall_s", "errors", "mismatches",
                   "timeouts", "rejected_503", "other", "compile_s",
                   "duration_ms", "rc")
#: ...and worse when LOWER (throughput, speedups, successes).
LOWER_IS_WORSE = ("rps", "qps", "value", "speedup", "mfu", "bw_util",
                  "answered", "ok")

#: Built-in per-metric tolerances — consulted AFTER any CLI
#: ``--tolerance`` rules (the caller always wins) and before
#: ``--default-tolerance``. The replica-sweep throughput/latency figures
#: are structurally noisy on shared CI rigs (N dispatcher threads
#: time-slicing few cores), so they gate with generous headroom; their
#: error/mismatch counters stay pinned exact by the CI
#: ``--require-equal`` flags, which this table never relaxes.
BUILTIN_TOLERANCES: List[Tuple[str, float]] = [
    ("*replica_sweep*rps", 2.0),
    ("*replica_sweep*p50_ms", 3.0),
    ("*replica_sweep*p99_ms", 3.0),
    ("*replica_speedup", 2.0),
    # Peer-replication bench (fault_tolerance.md §9): loopback push
    # throughput rides disk fsync + CPU CRC timing, and the one-chunk
    # repair smoke is a few tens of ms — both noisy on shared rigs.
    ("*replication_bench*push_rps", 2.0),
    ("*replication_bench*push_mb_s", 2.0),
    ("*replication_bench*repair_duration_ms", 3.0),
    # Hyperparameter-search A/B (PR 18): both arms are compile-heavy by
    # design (the serial arm's recompiles ARE the measured cost), and
    # compile time on shared rigs swings widely; the speedup ratio is
    # steadier than either wall-clock but still rides the same noise.
    ("*tune_bench*wall_s", 2.0),
    ("*tune_bench*speedup", 1.5),
    # Sharded-ingest A/B (bench_outofcore): both walls ride a
    # sleep-paced local HTTP link plus pandas parse on shared-rig CPU;
    # the speedup ratio cancels most of it but still jitters. The hard
    # ≥1.8x floor is asserted inside the bench itself — the tolerance
    # only gates run-over-run drift.
    ("*sharded_ingest*wall_s", 2.0),
    ("*sharded_ingest*speedup", 0.5),
]


def normalize(doc: Any, prefix: str = "",
              out: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    """Flatten one bench document of ANY shipped shape into
    ``{dotted.path: number}``. Driver wrappers unwrap to their
    ``parsed`` payload; lists index numerically; non-numeric leaves
    (metric names, units, command lines) drop out."""
    if out is None:
        out = {}
        # Driver-wrapper shape: the measurement lives under "parsed";
        # rc is kept (a failing bench run IS a regression).
        if isinstance(doc, dict) and "parsed" in doc and "cmd" in doc:
            if "rc" in doc:
                out["rc"] = float(doc.get("rc") or 0)
            doc = doc["parsed"]
    if isinstance(doc, dict):
        for key, val in sorted(doc.items()):
            name = f"{prefix}{key}"
            if isinstance(val, (dict, list)):
                normalize(val, f"{name}.", out)
            elif isinstance(val, bool):
                out[name] = 1.0 if val else 0.0
            elif isinstance(val, (int, float)):
                out[name] = float(val)
    elif isinstance(doc, list):
        for i, val in enumerate(doc):
            name = f"{prefix}{i}"
            if isinstance(val, (dict, list)):
                normalize(val, f"{name}.", out)
            elif isinstance(val, bool):
                out[name] = 1.0 if val else 0.0
            elif isinstance(val, (int, float)):
                out[name] = float(val)
    return out


def direction(path: str) -> Optional[str]:
    """"up" = worse when higher, "down" = worse when lower, None =
    informational (no gate). Judged on the path's last component."""
    leaf = path.rsplit(".", 1)[-1]
    for suffix in HIGHER_IS_WORSE:
        if leaf == suffix or leaf.endswith("_" + suffix):
            return "up"
    for suffix in LOWER_IS_WORSE:
        if leaf == suffix or leaf.endswith("_" + suffix):
            return "down"
    return None


def _tolerance_for(path: str, rules: List[Tuple[str, float]],
                   default: float) -> float:
    for pattern, tol in list(rules) + BUILTIN_TOLERANCES:
        if fnmatch.fnmatch(path, pattern):
            return tol
    return default


def diff(baseline: Dict[str, float], candidate: Dict[str, float],
         tolerances: Optional[List[Tuple[str, float]]] = None,
         default_tolerance: float = 0.15,
         require_equal: Optional[List[str]] = None) -> Dict[str, Any]:
    """Compare two normalized runs. A metric regresses when it moved in
    its worse direction by more than its tolerance (relative, against
    the baseline magnitude; a zero baseline gates on any worsening
    beyond the tolerance in absolute terms). Metrics present in only
    one run are reported, not failed — schemas may grow."""
    tolerances = tolerances or []
    require_equal = require_equal or []
    regressions: List[Dict[str, Any]] = []
    improvements: List[str] = []
    compared = 0
    for path in sorted(set(baseline) & set(candidate)):
        base, cand = baseline[path], candidate[path]
        pinned = any(fnmatch.fnmatch(path, p) for p in require_equal)
        dirn = direction(path)
        if dirn is None and not pinned:
            continue
        compared += 1
        worse = (cand - base) if (dirn == "up" or (pinned and dirn != "down")) \
            else (base - cand)
        if pinned:
            if worse > 0:
                regressions.append(
                    {"metric": path, "baseline": base, "candidate": cand,
                     "limit": base, "why": "pinned equal-or-better"})
            continue
        tol = _tolerance_for(path, tolerances, default_tolerance)
        scale = abs(base) if base else 1.0
        if worse > tol * scale:
            limit = (base + tol * scale) if dirn == "up" \
                else (base - tol * scale)
            regressions.append(
                {"metric": path, "baseline": base, "candidate": cand,
                 "limit": round(limit, 6),
                 "why": f"{dirn == 'up' and 'rose' or 'fell'} past "
                        f"{tol:.0%} tolerance"})
        elif worse < 0:
            improvements.append(path)
    return {
        "ok": not regressions,
        "compared": compared,
        "baseline_metrics": len(baseline),
        "candidate_metrics": len(candidate),
        "only_baseline": sorted(set(baseline) - set(candidate)),
        "only_candidate": sorted(set(candidate) - set(baseline)),
        "regressions": regressions,
        "improved": len(improvements),
    }


def load(path: str) -> Dict[str, float]:
    with open(path, encoding="utf-8") as f:
        return normalize(json.load(f))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.benchdiff",
        description="diff two bench runs; exit 1 on regression")
    ap.add_argument("baseline")
    ap.add_argument("candidate")
    ap.add_argument("--tolerance", action="append", default=[],
                    metavar="PATTERN=REL",
                    help="per-metric relative tolerance, e.g. "
                         "'*.p99_ms=0.5' (first match wins)")
    ap.add_argument("--default-tolerance", type=float, default=0.15,
                    help="relative tolerance for gated metrics without "
                         "a --tolerance match (default 0.15)")
    ap.add_argument("--require-equal", action="append", default=[],
                    metavar="PATTERN",
                    help="metrics that must be equal-or-better "
                         "regardless of tolerance (error counters)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    rules: List[Tuple[str, float]] = []
    for spec in args.tolerance:
        if "=" not in spec:
            ap.error(f"--tolerance {spec!r}: expected PATTERN=REL")
        pattern, _, raw = spec.rpartition("=")
        try:
            rules.append((pattern, float(raw)))
        except ValueError:
            ap.error(f"--tolerance {spec!r}: REL must be a number")

    report = diff(load(args.baseline), load(args.candidate),
                  tolerances=rules,
                  default_tolerance=args.default_tolerance,
                  require_equal=args.require_equal)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(f"benchdiff: {report['compared']} gated metrics compared "
              f"({report['baseline_metrics']} baseline / "
              f"{report['candidate_metrics']} candidate), "
              f"{report['improved']} improved")
        for r in report["regressions"]:
            print(f"  REGRESSION {r['metric']}: {r['baseline']:g} -> "
                  f"{r['candidate']:g} (limit {r['limit']:g}; {r['why']})")
        if report["ok"]:
            print("benchdiff: OK")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
